"""ComponentConfig, feature gates, and metrics (SURVEY.md §5)."""

import pytest

from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.features import (
    FeatureGates,
    GENERIC_WORKLOAD,
    TPU_BATCH_SCHEDULING,
    TPU_STATE_RESIDENCY,
)
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class TestFeatureGates:
    def test_defaults(self):
        g = FeatureGates()
        assert g.enabled(GENERIC_WORKLOAD)
        assert g.enabled(TPU_BATCH_SCHEDULING)

    def test_override_and_unknown(self):
        g = FeatureGates({TPU_BATCH_SCHEDULING: False, TPU_STATE_RESIDENCY: False})
        assert not g.enabled(TPU_BATCH_SCHEDULING)
        with pytest.raises(ValueError):
            FeatureGates({"NoSuchGate": True})

    def test_dependency_validation(self):
        with pytest.raises(ValueError):
            FeatureGates({TPU_BATCH_SCHEDULING: False})  # residency depends on it


class TestComponentConfig:
    def test_plugin_set_resolve(self):
        ps = PluginSet(enabled=(("TaintToleration", 5),), disabled=("ImageLocality",))
        resolved = dict(ps.resolve())
        assert resolved["TaintToleration"] == 5
        assert "ImageLocality" not in resolved

    def test_from_dict_profile(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{
                "schedulerName": "custom",
                "plugins": {"disabled": ["InterPodAffinity"]},
                "pluginConfig": [
                    {"name": "NodeResourcesFit",
                     "args": {"scoring_strategy": "MostAllocated"}}],
            }],
            "percentageOfNodesToScore": 20,
            "featureGates": {"GenericWorkload": True},
        })
        s = Scheduler(config=cfg)
        assert "custom" in s.profiles
        fw = s.profiles["custom"]
        assert fw.plugin("InterPodAffinity") is None
        assert fw.plugin("NodeResourcesFit").scoring_strategy == "MostAllocated"
        assert s.percentage_of_nodes_to_score == 20

    def test_custom_profile_schedules(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{"schedulerName": "custom"}]})
        s = Scheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        p = make_pod().name("p").req({"cpu": "1"}).scheduler_name("custom").obj()
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 1

    def test_device_gate_off_uses_host_path(self):
        cfg = SchedulerConfiguration.from_dict({
            "featureGates": {"TPUBatchScheduling": False,
                             "TPUStateResidency": False}})
        s = TPUScheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 1
        assert s.device_batches == 0


class TestMetrics:
    def test_schedule_attempt_series(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("fits").req({"cpu": "1"}).obj())
        s.clientset.create_pod(make_pod().name("huge").req({"cpu": "64"}).obj())
        s.run_until_idle()
        m = s.metrics
        assert m.schedule_attempts.value("scheduled", "default-scheduler") == 1
        assert m.schedule_attempts.value("unschedulable", "default-scheduler") >= 1
        assert m.scheduling_attempt_duration.count("scheduled", "default-scheduler") == 1
        text = s.expose_metrics()
        assert "scheduler_schedule_attempts_total" in text
        assert 'scheduler_pending_pods{queue="unschedulable"}' in text

    def test_preemption_metrics(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("low").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        s.clientset.create_pod(make_pod().name("hi").req({"cpu": "2"}).priority(9).obj())
        s.run_until_idle()
        assert s.metrics.preemption_attempts.value() >= 1
        assert s.metrics.preemption_victims.count() == 1

    def test_batch_metrics(self):
        s = TPUScheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "8", "pods": 20}).obj())
        for i in range(5):
            s.clientset.create_pod(make_pod().name(f"p{i}").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.metrics.batch_attempts.value("dispatched") >= 1
        assert s.metrics.batch_size.count() >= 1


def test_pre_bind_pre_flight_skips_and_runs():
    """PreBindPreFlight (runtime/framework.go:1875): all-Skip bypasses the
    PreBind phase; a declaring plugin still runs when it has work."""
    from kubernetes_tpu.core.framework import CycleState, Framework, OK, Status

    ran = []

    class Flighty:
        name = "Flighty"

        def __init__(self, skip):
            self._skip = skip

        def pre_bind_pre_flight(self, state, pod, node):
            return Status.skip() if self._skip else OK

        def pre_bind(self, state, pod, node):
            ran.append(self.name)
            return OK

    from kubernetes_tpu.testing.wrappers import make_pod
    pod = make_pod().name("p").obj()

    fw = Framework(plugins=[(Flighty(skip=True), 0)])
    state = CycleState()
    st = fw.run_pre_bind_pre_flight(state, pod, "n0")
    assert st.is_skip()
    assert "Flighty" in state.skip_pre_bind_plugins

    fw2 = Framework(plugins=[(Flighty(skip=False), 0)])
    state2 = CycleState()
    st2 = fw2.run_pre_bind_pre_flight(state2, pod, "n0")
    assert st2.is_success() and not st2.is_skip()
    fw2.run_pre_bind_plugins(state2, pod, "n0")
    assert ran == ["Flighty"]


def test_extension_point_latency_recorded():
    """framework_extension_point_duration_seconds fills per point during
    host scheduling cycles (metrics.go:265-615 series; perf artifact
    carries per-point percentiles)."""
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    cs = FakeClientset()
    sched = Scheduler(clientset=cs)
    cs.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
    cs.create_node(make_node().name("n1").capacity({"cpu": "4", "pods": 10}).obj())
    cs.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
    sched.run_until_idle()
    hist = sched.metrics.framework_extension_point_duration
    for point in ("PreFilter", "Filter", "PreScore", "Score", "Reserve",
                  "Permit", "Bind"):
        assert hist.count(point, "Success", "") >= 1, point


def test_metric_async_recorder_flushes_off_thread():
    """metric_recorder.go analogue: observations buffer on the hot path and
    land in the histogram via the flusher thread; overflow drops are
    counted, close() drains."""
    import time as _t

    from kubernetes_tpu.core.metrics import Histogram, MetricAsyncRecorder

    h = Histogram("test_hist", "t", ("label",))
    rec = MetricAsyncRecorder(interval=0.01, capacity=8)
    for i in range(6):
        rec.observe(h, 0.001 * i, "x")
    deadline = _t.monotonic() + 5
    while _t.monotonic() < deadline and h.count("x") < 6:
        _t.sleep(0.005)
    assert h.count("x") == 6
    # overflow drops (non-blocking send semantics)
    rec._stop.set(); rec._thread.join(timeout=2)  # park the flusher
    for i in range(20):
        rec.observe(h, 0.1, "x")
    assert rec.dropped == 12
    rec.flush_now()
    assert h.count("x") == 14


def test_scheduler_configuration_validation():
    """ValidateKubeSchedulerConfiguration (validation.go:38): range checks,
    profile uniqueness, extender verb/weight requirements."""
    from kubernetes_tpu.core.config import ProfileConfig, SchedulerConfiguration

    assert SchedulerConfiguration().validate() == []

    bad = SchedulerConfiguration(
        percentage_of_nodes_to_score=150,
        pod_initial_backoff_seconds=0,
        pod_max_backoff_seconds=-1,
        max_batch=0,
        profiles=[ProfileConfig(scheduler_name="a"),
                  ProfileConfig(scheduler_name="a")],
        extenders=[{"filterVerb": "filter"},         # no urlPrefix
                   {"urlPrefix": "http://x", "weight": 0}])  # no verb, bad weight
    errs = bad.validate()
    joined = "\n".join(errs)
    assert "percentageOfNodesToScore" in joined
    assert "podInitialBackoffSeconds" in joined
    assert "podMaxBackoffSeconds" in joined
    assert "maxBatch" in joined
    assert "Duplicate" in joined
    assert "urlPrefix" in joined
    assert "at least one verb" in joined
    assert "positive integer" in joined


REFERENCE_SERIES = {
    # pkg/scheduler/metrics/metrics.go:265-615 — all 45 registered names
    # (grep 'Name:' over the file), prefixed scheduler_ by the subsystem.
    "async_api_call_execution_duration_seconds",
    "async_api_call_execution_total",
    "batch_attempts_total",
    "batch_cache_flushed_total",
    "cache_size",
    "dra_bindingconditions_allocations_total",
    "dra_bindingconditions_wait_duration_seconds",
    "event_handling_duration_seconds",
    "framework_extension_point_duration_seconds",
    "generated_placements_total",
    "get_node_hint_duration_seconds",
    "goroutines",
    "inflight_events",
    "pending_async_api_calls",
    "pending_pods",
    "permit_wait_duration_seconds",
    "placement_evaluation_duration_seconds",
    "placement_evaluations_total",
    "plugin_evaluation_total",
    "plugin_execution_duration_seconds",
    "pod_scheduled_after_flush_total",
    "pod_scheduling_attempts",
    "pod_scheduling_sli_duration_seconds",
    "podgroup_schedule_attempts_total",
    "podgroup_scheduling_algorithm_duration_seconds",
    "podgroup_scheduling_attempt_duration_seconds",
    "preemption_attempts_total",
    "preemption_evaluation_duration_seconds",
    "preemption_execution_duration_seconds",
    "preemption_goroutines_duration_seconds",
    "preemption_goroutines_execution_total",
    "preemption_pdb_violations_total",
    "preemption_victims",
    "preemption_workload_disruptions",
    "queue_incoming_entities_total",
    "queue_incoming_pods_total",
    "queued_entities",
    "queueing_hint_execution_duration_seconds",
    "schedule_attempts_total",
    "scheduling_algorithm_duration_seconds",
    "scheduling_attempt_duration_seconds",
    "store_schedule_results_duration_seconds",
    "unschedulable_pods",
    "workload_preemption_attempts_total",
    "workload_preemption_victims",
}


def test_metric_name_parity_with_reference():
    """The registered series names cover the reference scheduler's full set
    (metrics/metrics.go:265-615) — the round-4 VERDICT's metrics sweep."""
    from kubernetes_tpu.core.metrics import SchedulerMetrics

    m = SchedulerMetrics()
    registered = {metric.name for metric in m.registry._metrics}
    expected = {f"scheduler_{n}" for n in REFERENCE_SERIES}
    missing = expected - registered
    assert not missing, f"missing reference series: {sorted(missing)}"
    extra = registered - expected
    # Our additions beyond the reference set (device-path + resilience
    # series, docs/RESILIENCE.md; shard-plane series, docs/SHARDING.md).
    assert extra <= {"scheduler_batch_size",
                     "scheduler_e2e_scheduling_duration_seconds",
                     "scheduler_podgroup_generated_placements",
                     "scheduler_async_api_call_retries_total",
                     "scheduler_device_path_fallback_total",
                     "scheduler_device_path_breaker_open",
                     "scheduler_plan_rebuild_total",
                     "scheduler_plan_rebuild_dirty_rows_total",
                     "scheduler_hint_cache_hits_total",
                     "scheduler_hint_cache_misses_total",
                     "scheduler_hint_cache_invalidations_total",
                     "scheduler_hint_validation_duration_seconds",
                     "scheduler_bind_conflict_total",
                     "scheduler_shard_owned_shards",
                     "scheduler_shard_lease_renewals_total",
                     "scheduler_shard_adoptions_total",
                     "scheduler_watch_decoded_events",
                     "scheduler_watch_decoded_bytes",
                     "scheduler_queue_starvation_seconds"}, extra


def test_new_series_populate_during_scheduling():
    """A mixed run moves the newly wired series (not just registers them)."""
    from kubernetes_tpu.core import FakeClientset, Scheduler
    from kubernetes_tpu.testing import make_node, make_pod

    cs = FakeClientset()
    s = Scheduler(clientset=cs)
    for i in range(4):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "4", "pods": 10}).obj())
    for i in range(6):
        cs.create_pod(make_pod().name(f"p{i}").req({"cpu": "1"}).obj())
    s.run_until_idle()
    m = s.metrics
    assert m.scheduling_algorithm_duration.count() == 6
    assert m.pod_scheduling_attempts.count() == 6
    assert m.event_handling_duration.count("pod") >= 6
    assert m.event_handling_duration.count("node") == 4
    # preemption moves the preemption series
    cs.create_pod(make_pod().name("hi").req({"cpu": "4"}).priority(100).obj())
    s.run_until_idle()
    for _ in range(20):
        s.process_async_api_errors()
        s.run_until_idle()
    assert m.preemption_evaluation_duration.count() >= 1
    assert m.preemption_execution_duration.count() >= 1
    assert m.preemption_goroutines_execution_total.value("success") >= 1
    # exposure includes callback gauges without error
    text = s.expose_metrics()
    assert "scheduler_inflight_events" in text
    assert "scheduler_queued_entities" in text


def test_metrics_resources_endpoint():
    from kubernetes_tpu.core import FakeClientset, Scheduler
    from kubernetes_tpu.core.server import SchedulerServer
    from kubernetes_tpu.testing import make_node, make_pod
    from urllib.request import urlopen

    cs = FakeClientset()
    s = Scheduler(clientset=cs)
    cs.create_node(make_node().name("n0").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    cs.create_pod(make_pod().name("p0").req({"cpu": "500m", "memory": "1Gi"}).obj())
    s.run_until_idle()
    srv = SchedulerServer(s)
    port = srv.serve(0)
    body = urlopen(f"http://127.0.0.1:{port}/metrics/resources", timeout=5).read().decode()
    srv.shutdown()
    assert "kube_pod_resource_request" in body
    assert 'resource="cpu"' in body and 'phase="Running"' in body
