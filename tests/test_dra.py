"""DynamicResources (DRA) plugin: structured-parameter claim allocation
(reference plugins/dynamicresources/)."""

from kubernetes_tpu.api.dra import (
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceSlice,
)
from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _dra_sched():
    cfg = SchedulerConfiguration(profiles=[ProfileConfig(
        plugins=PluginSet(enabled=(("DynamicResources", 0),)))])
    return Scheduler(config=cfg, deterministic_ties=True)


def _gpu_node(s, name, n_gpus, gpu_type="a100"):
    s.clientset.create_node(
        make_node().name(name).capacity({"cpu": "16", "pods": 20}).obj())
    s.clientset.create_resource_slice(ResourceSlice(
        node_name=name, driver="gpu.example.com",
        devices=[Device(name=f"{name}-gpu{i}", attributes={"type": gpu_type})
                 for i in range(n_gpus)]))


def _claim_pod(s, pod_name, claim_name, count=1, selectors=None, device_class=""):
    s.clientset.create_resource_claim(ResourceClaim(
        name=claim_name,
        requests=[DeviceRequest(count=count, selectors=selectors or {},
                                device_class=device_class)]))
    p = make_pod().name(pod_name).req({"cpu": "1"}).obj()
    p.resource_claims.append(claim_name)
    s.clientset.create_pod(p)
    return p


class TestDynamicResources:
    def test_allocates_devices_on_fitting_node(self):
        s = _dra_sched()
        _gpu_node(s, "cpu-only", 0)
        _gpu_node(s, "gpu-node", 2)
        _claim_pod(s, "p", "claim-a", count=2)
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["gpu-node"]
        claim = s.clientset.resource_claims["default/claim-a"]
        assert claim.allocated_node == "gpu-node"
        assert len(claim.allocations) == 2
        assert claim.reserved_for  # pod recorded

    def test_devices_are_exclusive(self):
        s = _dra_sched()
        _gpu_node(s, "gpu-node", 1)
        _claim_pod(s, "p1", "c1", count=1)
        _claim_pod(s, "p2", "c2", count=1)
        s.run_until_idle()
        assert s.scheduled == 1  # second claim can't get the only GPU

    def test_selector_matching(self):
        s = _dra_sched()
        _gpu_node(s, "a100-node", 1, gpu_type="a100")
        _gpu_node(s, "h100-node", 1, gpu_type="h100")
        _claim_pod(s, "p", "c", selectors={"type": "h100"})
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["h100-node"]

    def test_device_class_selectors(self):
        s = _dra_sched()
        s.clientset.create_device_class(DeviceClass(
            name="big-gpu", selectors={"type": "h100"}))
        _gpu_node(s, "small", 4, gpu_type="a100")
        _gpu_node(s, "big", 1, gpu_type="h100")
        _claim_pod(s, "p", "c", device_class="big-gpu")
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["big"]

    def test_preallocated_claim_pins_node(self):
        s = _dra_sched()
        _gpu_node(s, "n0", 1)
        _gpu_node(s, "n1", 1)
        claim = ResourceClaim(name="pinned", requests=[DeviceRequest(count=1)])
        claim.allocated_node = "n1"
        s.clientset.create_resource_claim(claim)
        p = make_pod().name("p").req({"cpu": "1"}).obj()
        p.resource_claims.append("pinned")
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]

    def test_missing_claim_unresolvable(self):
        s = _dra_sched()
        _gpu_node(s, "n0", 1)
        p = make_pod().name("p").req({"cpu": "1"}).obj()
        p.resource_claims.append("no-such-claim")
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 0


class TestExpressionSelectors:
    """Structured parameters with CEL-equivalent device selector expressions
    (staging dynamic-resource-allocation/cel; DeviceSelector.cel.expression)."""

    def _cluster(self):
        from kubernetes_tpu.api.dra import Device, ResourceSlice
        from kubernetes_tpu.testing.wrappers import make_node
        s = _dra_sched()
        cs = s.clientset
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
            model = "a100" if i % 2 == 0 else "t4"
            cs.create_resource_slice(ResourceSlice(
                node_name=f"n{i}", driver="gpu.example.com",
                devices=[Device(name=f"gpu-{i}-{j}",
                                attributes={"model": model, "mem": "40" if model == "a100" else "16"})
                         for j in range(2)]))
        return cs, s

    def test_expression_picks_matching_devices(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.testing.wrappers import make_pod
        cs, s = self._cluster()
        claim = ResourceClaim(name="big-gpu", requests=[DeviceRequest(
            name="gpu", count=1,
            expression='device.attributes["model"] == "a100" and device.attributes["mem"] >= 32')])
        cs.create_resource_claim(claim)
        p = make_pod().name("train").req({"cpu": "1"}).obj()
        p.resource_claims = ["big-gpu"]
        cs.create_pod(p)
        s.run_until_idle()
        assert p.node_name in ("n0", "n2"), p.node_name  # a100 nodes only
        assert claim.allocated and claim.allocated_node == p.node_name

    def test_expression_no_match_unschedulable(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.testing.wrappers import make_pod
        cs, s = self._cluster()
        claim = ResourceClaim(name="h100", requests=[DeviceRequest(
            name="gpu", count=1,
            expression='device.attributes["model"] == "h100"')])
        cs.create_resource_claim(claim)
        p = make_pod().name("train").req({"cpu": "1"}).obj()
        p.resource_claims = ["h100"]
        cs.create_pod(p)
        s.run_until_idle()
        assert not p.node_name and s.failures >= 1

    def test_alloc_claims_opcode_respects_expressions(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.plugins.dynamicresources import allocate_pending_claims
        cs, s = self._cluster()
        for i in range(3):
            cs.create_resource_claim(ResourceClaim(
                name=f"c{i}", requests=[DeviceRequest(
                    name="gpu", count=1,
                    expression='device.attributes["model"] == "t4"')]))
        n = allocate_pending_claims(cs)
        assert n == 3
        nodes = {cs.resource_claims[f"default/c{i}"].allocated_node for i in range(3)}
        assert nodes <= {"n1", "n3"}

    def test_disallowed_expression_rejected(self):
        import pytest
        from kubernetes_tpu.api.dra import ExpressionError, compile_device_expression
        for bad in ('__import__("os").system("true")', 'open("/etc/passwd")',
                    'device.__class__', 'x + 1'):
            with pytest.raises(ExpressionError):
                compile_device_expression(bad)


def _dra_sched_pair(**kw):
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.config import SchedulerConfiguration
    from kubernetes_tpu.core.registry import DEFAULT_PLUGINS, build_framework
    from kubernetes_tpu.core.scheduler import Scheduler

    cs = FakeClientset()
    plugins = DEFAULT_PLUGINS + (("DynamicResources", 0),)
    cfg = SchedulerConfiguration(feature_gates={
        "DynamicResourceAllocation": True,
        "DRAExtendedResource": True,
        "DRANodeAllocatableResources": True,
    })
    sched = Scheduler(clientset=cs, deterministic_ties=True, config=cfg,
                      profile_factory=lambda h: {
                          "default-scheduler": build_framework(h, plugins=plugins)},
                      **kw)
    return cs, sched


def test_extended_resources_backed_by_dra():
    """extendeddynamicresources.go: a pod requesting example.com/gpu with a
    mapping DeviceClass allocates DRA devices on a node with no device
    plugin capacity; the special in-memory claim becomes a real object at
    PreBind with the pod recorded in reservedFor."""
    from kubernetes_tpu.api.dra import Device, DeviceClass, ResourceSlice
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    cs, sched = _dra_sched_pair()
    cs.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
    cs.create_resource_slice(ResourceSlice(
        node_name="n0", driver="gpu.example.com",
        devices=[Device(name=f"gpu-{i}") for i in range(4)]))
    cs.create_device_class(DeviceClass(
        name="gpus", extended_resource_name="example.com/gpu"))
    pod = make_pod().name("p").req({"cpu": "1", "example.com/gpu": 2}).obj()
    cs.create_pod(pod)
    sched.run_until_idle()
    assert cs.bindings.get(pod.uid) == "n0"
    claim = cs.resource_claims.get("default/p-extended-resources")
    assert claim is not None
    assert claim.allocated_node == "n0"
    assert len(claim.allocations) == 2
    assert pod.uid in claim.reserved_for
    assert pod.extended_resource_claim_status["claim"] == claim.key


def test_extended_resources_satisfied_by_device_plugin():
    """When the node's device plugin already advertises the extended
    resource, no DRA allocation happens (filterExtendedResources split)."""
    from kubernetes_tpu.api.dra import DeviceClass
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    cs, sched = _dra_sched_pair()
    cs.create_node(make_node().name("n0").capacity(
        {"cpu": "8", "pods": 10, "example.com/gpu": 4}).obj())
    cs.create_device_class(DeviceClass(
        name="gpus", extended_resource_name="example.com/gpu"))
    pod = make_pod().name("p").req({"cpu": "1", "example.com/gpu": 2}).obj()
    cs.create_pod(pod)
    sched.run_until_idle()
    assert cs.bindings.get(pod.uid) == "n0"
    assert cs.resource_claims.get("default/p-extended-resources") is None


def test_dra_device_node_allocatable_consumption():
    """nodeallocatabledynamicresources.go: an allocated device's declared
    node-resource consumption counts against the node's allocatable."""
    from kubernetes_tpu.api.dra import Device, DeviceRequest, ResourceClaim, ResourceSlice
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    cs, sched = _dra_sched_pair()
    cs.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
    cs.create_resource_slice(ResourceSlice(
        node_name="n0", driver="x.csi",
        devices=[Device(name="d0", consumes={"cpu": "3"})]))
    # pod requests 2 cpu; device consumes 3 more => 5 > 4 allocatable
    cs.create_resource_claim(ResourceClaim(
        name="c", requests=[DeviceRequest(name="r", count=1)]))
    pod = make_pod().name("p").req({"cpu": "2"}).obj()
    pod.resource_claims = ["c"]
    cs.create_pod(pod)
    sched.run_until_idle()
    assert cs.bindings.get(pod.uid) is None

    # a lighter pod fits alongside the device's consumption
    cs.create_resource_claim(ResourceClaim(
        name="c2", requests=[DeviceRequest(name="r", count=1)]))
    pod2 = make_pod().name("p2").req({"cpu": "1"}).obj()
    pod2.resource_claims = ["c2"]
    cs.create_pod(pod2)
    sched.run_until_idle()
    assert cs.bindings.get(pod2.uid) == "n0"


def test_typed_capacity_expression():
    """Typed CEL capacity semantics: quantity strings compare numerically
    (device.capacity["memory"] >= 40Gi-in-bytes for "80Gi")."""
    from kubernetes_tpu.api.dra import Device, compile_device_expression

    m = compile_device_expression(
        'device.capacity["memory"] >= 42949672960')
    assert m(Device(name="d", capacity={"memory": "80Gi"}), "drv")
    assert not m(Device(name="d", capacity={"memory": "16Gi"}), "drv")


def test_claim_template_pods_ride_device_and_match_host():
    """Claim-template pods (one unallocated single-request claim each):
    the kernel models free matching devices as the counted aux resource;
    the host commit allocates on the chosen node — assignments AND device
    exhaustion behavior identical to the host oracle."""
    from kubernetes_tpu.api.dra import Device, DeviceRequest, ResourceClaim, ResourceSlice
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.registry import DEFAULT_PLUGINS, build_framework
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    def run(cls):
        cs = FakeClientset()
        plugins = DEFAULT_PLUGINS + (("DynamicResources", 0),)
        kw = {"deterministic_ties": True} if cls is Scheduler else {}
        sched = cls(clientset=cs, profile_factory=lambda h: {
            "default-scheduler": build_framework(h, plugins=plugins)}, **kw)
        for i in range(8):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": "32", "pods": 110}).obj())
            cs.create_resource_slice(ResourceSlice(
                node_name=f"n{i}", driver="gpu.x",
                devices=[Device(name=f"n{i}-d{j}",
                                attributes={"model": "a100" if j < 2 else "v100"})
                         for j in range(4)]))
        pods = []
        # 20 pods x 1 matching device; only 16 matching devices exist
        for i in range(20):
            cs.create_resource_claim(ResourceClaim(
                name=f"c{i}", requests=[DeviceRequest(
                    name="r", count=1,
                    expression='device.attributes["model"] == "a100"')]))
            p = make_pod().name(f"p{i}").req({"cpu": "100m"}).obj()
            p.resource_claims = [f"c{i}"]
            cs.create_pod(p)
            pods.append(p)
        sched.run_until_idle()
        return cs, sched, pods

    cs_h, host, ph = run(Scheduler)
    cs_d, dev, pd = run(TPUScheduler)
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd}
    assert hb == db
    assert sum(1 for v in db.values() if v) == 16  # device pool exhausted
    assert dev.device_scheduled >= 14
    # committed claims carry real allocations on the bound node
    for p in pd:
        node = cs_d.bindings.get(p.uid)
        claim = cs_d.resource_claims[f"default/{p.resource_claims[0]}"]
        if node:
            assert claim.allocated_node == node
            assert len(claim.allocations) == 1
            assert p.uid in claim.reserved_for
        else:
            assert not claim.allocated


def test_quantity_string_equality_in_expressions():
    """Typed quantities compare against the ORIGINAL suffixed string form
    too: coercion to numbers must not silently break
    device.capacity["x"] == "40Gi" (round-4 advisor finding)."""
    from kubernetes_tpu.api.dra import Device, compile_device_expression

    d = Device(name="d", capacity={"memory": "40Gi"},
               attributes={"model": "a100", "count": "8"})
    assert compile_device_expression(
        'device.capacity["memory"] == "40Gi"')(d, "drv")
    assert compile_device_expression(
        'device.capacity["memory"] == 42949672960')(d, "drv")
    assert compile_device_expression(
        'device.attributes["count"] == "8"')(d, "drv")
    assert compile_device_expression(
        'device.attributes["count"] >= "4"')(d, "drv")
    assert not compile_device_expression(
        'device.capacity["memory"] == "16Gi"')(d, "drv")
    # non-numeric strings still compare as strings
    assert compile_device_expression(
        'device.attributes["model"] == "a100"')(d, "drv")


def test_quantity_hash_eq_consistency():
    """ADVICE r5 regression: coerced quantity values must satisfy the
    hash/eq contract (a == b ⇒ hash(a) == hash(b)) for EVERY pairing of
    coerced, raw-string, and plain-numeric forms — so mixing them in one
    set or dict is well-defined. Cross-type string equality was dropped
    (expression string literals coerce at compile time instead,
    _ConstCoercer; see test_quantity_string_equality_in_expressions)."""
    from kubernetes_tpu.api.dra import _CoercingMap

    q8 = _CoercingMap._coerce("8")
    q25 = _CoercingMap._coerce("2.5")
    qgi = _CoercingMap._coerce("40Gi")
    forms = [q8, "8", 8, q25, 2.5, "2.5", qgi, 40 * 1024 ** 3, "40Gi"]
    for a in forms:
        for b in forms:
            if a == b:
                assert hash(a) == hash(b), (a, b)
    # one set/dict holding BOTH forms: coerced collapses with the number,
    # the raw string stays a distinct, reachable member
    s = {q8, "8", 8}
    assert len(s) == 2 and 8 in s and "8" in s
    d = {q8: "qty", "8": "raw"}
    assert len(d) == 2 and d[8] == "qty" and d["8"] == "raw"
    # ordering against suffixed strings still coerces (no hash contract)
    assert qgi >= "32Gi" and q8 < "16"


def test_const_coercion_scoped_to_quantity_map_comparisons():
    """The compile-time coercion must ONLY touch comparator operands of the
    two quantity maps: subscript KEYS stay literal strings (the map is
    string-keyed), plain-string fields compare as strings, and `in`
    membership against a quantity map coerces tuple members."""
    from kubernetes_tpu.api.dra import Device, compile_device_expression

    d = Device(name="0", attributes={"8": "yes", "count": "8",
                                     "model": "a100"})
    # quantity-shaped SUBSCRIPT KEY: looked up as the string "8"
    assert compile_device_expression(
        'device.attributes["8"] == "yes"')(d, "drv")
    # quantity-shaped literal vs a PLAIN-STRING field: string semantics
    assert compile_device_expression('device.name == "0"')(d, "drv")
    assert not compile_device_expression('device.name == "1"')(d, "drv")
    # membership against a quantity map coerces the tuple members
    assert compile_device_expression(
        'device.attributes["count"] in ("4", "8")')(d, "drv")
    assert compile_device_expression(
        'device.attributes["model"] in ("a100", "h100")')(d, "drv")


def test_coerced_memo_invalidates_on_map_replacement():
    """Replacing a device's attribute/capacity maps (the copy-on-write
    mutation contract) must invalidate the memoized coerced views — stale
    CEL values were the round-4 advisor finding."""
    from kubernetes_tpu.api.dra import Device, compile_device_expression

    d = Device(name="d", attributes={"model": "a100"})
    m = compile_device_expression('device.attributes["model"] == "a100"')
    assert m(d, "drv")
    d.attributes = {"model": "h100"}  # slice update replaces the map
    assert not m(d, "drv")
    assert compile_device_expression(
        'device.attributes["model"] == "h100"')(d, "drv")
