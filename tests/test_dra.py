"""DynamicResources (DRA) plugin: structured-parameter claim allocation
(reference plugins/dynamicresources/)."""

from kubernetes_tpu.api.dra import (
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceSlice,
)
from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _dra_sched():
    cfg = SchedulerConfiguration(profiles=[ProfileConfig(
        plugins=PluginSet(enabled=(("DynamicResources", 0),)))])
    return Scheduler(config=cfg, deterministic_ties=True)


def _gpu_node(s, name, n_gpus, gpu_type="a100"):
    s.clientset.create_node(
        make_node().name(name).capacity({"cpu": "16", "pods": 20}).obj())
    s.clientset.create_resource_slice(ResourceSlice(
        node_name=name, driver="gpu.example.com",
        devices=[Device(name=f"{name}-gpu{i}", attributes={"type": gpu_type})
                 for i in range(n_gpus)]))


def _claim_pod(s, pod_name, claim_name, count=1, selectors=None, device_class=""):
    s.clientset.create_resource_claim(ResourceClaim(
        name=claim_name,
        requests=[DeviceRequest(count=count, selectors=selectors or {},
                                device_class=device_class)]))
    p = make_pod().name(pod_name).req({"cpu": "1"}).obj()
    p.resource_claims.append(claim_name)
    s.clientset.create_pod(p)
    return p


class TestDynamicResources:
    def test_allocates_devices_on_fitting_node(self):
        s = _dra_sched()
        _gpu_node(s, "cpu-only", 0)
        _gpu_node(s, "gpu-node", 2)
        _claim_pod(s, "p", "claim-a", count=2)
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["gpu-node"]
        claim = s.clientset.resource_claims["default/claim-a"]
        assert claim.allocated_node == "gpu-node"
        assert len(claim.allocations) == 2
        assert claim.reserved_for  # pod recorded

    def test_devices_are_exclusive(self):
        s = _dra_sched()
        _gpu_node(s, "gpu-node", 1)
        _claim_pod(s, "p1", "c1", count=1)
        _claim_pod(s, "p2", "c2", count=1)
        s.run_until_idle()
        assert s.scheduled == 1  # second claim can't get the only GPU

    def test_selector_matching(self):
        s = _dra_sched()
        _gpu_node(s, "a100-node", 1, gpu_type="a100")
        _gpu_node(s, "h100-node", 1, gpu_type="h100")
        _claim_pod(s, "p", "c", selectors={"type": "h100"})
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["h100-node"]

    def test_device_class_selectors(self):
        s = _dra_sched()
        s.clientset.create_device_class(DeviceClass(
            name="big-gpu", selectors={"type": "h100"}))
        _gpu_node(s, "small", 4, gpu_type="a100")
        _gpu_node(s, "big", 1, gpu_type="h100")
        _claim_pod(s, "p", "c", device_class="big-gpu")
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["big"]

    def test_preallocated_claim_pins_node(self):
        s = _dra_sched()
        _gpu_node(s, "n0", 1)
        _gpu_node(s, "n1", 1)
        claim = ResourceClaim(name="pinned", requests=[DeviceRequest(count=1)])
        claim.allocated_node = "n1"
        s.clientset.create_resource_claim(claim)
        p = make_pod().name("p").req({"cpu": "1"}).obj()
        p.resource_claims.append("pinned")
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]

    def test_missing_claim_unresolvable(self):
        s = _dra_sched()
        _gpu_node(s, "n0", 1)
        p = make_pod().name("p").req({"cpu": "1"}).obj()
        p.resource_claims.append("no-such-claim")
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 0


class TestExpressionSelectors:
    """Structured parameters with CEL-equivalent device selector expressions
    (staging dynamic-resource-allocation/cel; DeviceSelector.cel.expression)."""

    def _cluster(self):
        from kubernetes_tpu.api.dra import Device, ResourceSlice
        from kubernetes_tpu.testing.wrappers import make_node
        s = _dra_sched()
        cs = s.clientset
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
            model = "a100" if i % 2 == 0 else "t4"
            cs.create_resource_slice(ResourceSlice(
                node_name=f"n{i}", driver="gpu.example.com",
                devices=[Device(name=f"gpu-{i}-{j}",
                                attributes={"model": model, "mem": "40" if model == "a100" else "16"})
                         for j in range(2)]))
        return cs, s

    def test_expression_picks_matching_devices(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.testing.wrappers import make_pod
        cs, s = self._cluster()
        claim = ResourceClaim(name="big-gpu", requests=[DeviceRequest(
            name="gpu", count=1,
            expression='device.attributes["model"] == "a100" and device.attributes["mem"] >= 32')])
        cs.create_resource_claim(claim)
        p = make_pod().name("train").req({"cpu": "1"}).obj()
        p.resource_claims = ["big-gpu"]
        cs.create_pod(p)
        s.run_until_idle()
        assert p.node_name in ("n0", "n2"), p.node_name  # a100 nodes only
        assert claim.allocated and claim.allocated_node == p.node_name

    def test_expression_no_match_unschedulable(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.testing.wrappers import make_pod
        cs, s = self._cluster()
        claim = ResourceClaim(name="h100", requests=[DeviceRequest(
            name="gpu", count=1,
            expression='device.attributes["model"] == "h100"')])
        cs.create_resource_claim(claim)
        p = make_pod().name("train").req({"cpu": "1"}).obj()
        p.resource_claims = ["h100"]
        cs.create_pod(p)
        s.run_until_idle()
        assert not p.node_name and s.failures >= 1

    def test_alloc_claims_opcode_respects_expressions(self):
        from kubernetes_tpu.api.dra import DeviceRequest, ResourceClaim
        from kubernetes_tpu.plugins.dynamicresources import allocate_pending_claims
        cs, s = self._cluster()
        for i in range(3):
            cs.create_resource_claim(ResourceClaim(
                name=f"c{i}", requests=[DeviceRequest(
                    name="gpu", count=1,
                    expression='device.attributes["model"] == "t4"')]))
        n = allocate_pending_claims(cs)
        assert n == 3
        nodes = {cs.resource_claims[f"default/c{i}"].allocated_node for i in range(3)}
        assert nodes <= {"n1", "n3"}

    def test_disallowed_expression_rejected(self):
        import pytest
        from kubernetes_tpu.api.dra import ExpressionError, compile_device_expression
        for bad in ('__import__("os").system("true")', 'open("/etc/passwd")',
                    'device.__class__', 'x + 1'):
            with pytest.raises(ExpressionError):
                compile_device_expression(bad)
