"""Descheduler HA chaos (PR 20, docs/DESCHEDULE.md § exactly-once): two
descheduler PROCESSES race the shared `descheduler` lease over a
3-replica control plane, and we ``kill -9`` the ACTIVE one mid-eviction-
wave. The standby must take over inside the lease TTL and finish the
wave exactly-once: intents are a pure function of the snapshot
(`uid@node`), so the survivor re-derives the dead incumbent's plan
verbatim and the server-side eviction ledger absorbs any overlap as
`already=True` replays. The gang moves whole or not at all — quiesce may
not leave a PodGroup partially evicted."""

import json
import time
from urllib import request as urlrequest
from urllib.error import HTTPError, URLError

import pytest

from kubernetes_tpu.controllers.evictor import intent_for
from kubernetes_tpu.core.apiserver import (EVICTED_ANNOTATION,
                                           node_to_wire, pod_to_wire)
from kubernetes_tpu.shard.harness import (_env, _repo_root,
                                          start_descheduler,
                                          stop_controller)
from kubernetes_tpu.testing.faults import ReplicaSet, drain_pipe
from kubernetes_tpu.testing.wrappers import make_node, make_pod

LEASE = 1.2
HOT = "hot"
GANG = ("gang-0", "gang-1", "gang-2")


def _call(base, method, path, body=None, timeout=30.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urlrequest.Request(base + path, data=data, method=method,
                            headers={"Content-Type": "application/json"})
    with urlrequest.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def _any(urls, method, path, body=None, timeout=10.0):
    last = None
    for url in urls:
        try:
            return _call(url, method, path, body, timeout=timeout)
        except HTTPError as e:
            if e.code in (421, 503):
                last = e
                continue
            raise
        except URLError as e:
            last = e
            continue
    raise last if last is not None else AssertionError("no replicas")


def _get_text(base, path, timeout=10.0):
    with urlrequest.urlopen(base + path, timeout=timeout) as resp:
        return resp.read().decode()


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"series {name} not exposed")


def _wait(pred, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _active_manager(managers):
    """(proc, metrics_url) of the manager whose gauge reads ACTIVE."""
    for proc, url in managers:
        if proc.poll() is not None:
            continue
        try:
            text = _get_text(url, "/metrics", timeout=5.0)
        except Exception:  # noqa: BLE001 - scrape raced a death
            continue
        if _metric(text, "descheduler_manager_active") == 1:
            return proc, url
    return None


def _evictions_total(base):
    return _metric(_get_text(base, "/metrics"),
                   "apiserver_pod_evictions_total")


@pytest.mark.chaos
def test_active_kill9_mid_wave_exactly_once_gang_whole(tmp_path):
    """SIGKILL the ACTIVE descheduler mid-eviction-wave. The standby
    CASes the lease inside the TTL, re-derives the SAME `uid@node`
    intents from its own snapshot, and finishes draining the hot node.
    Quiesce invariants: every evicted pod was evicted exactly ONCE
    (census == counter), replaying every committed intent answers
    `already=True` without moving the counter, the 3-pod gang is all-
    pending or all-bound (never split), and every intent the survivor
    planned matches the deterministic derivation."""
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=1.5, snapshot_every=100_000)
    urls = [rs.leader_url] + list(rs.follower_urls)
    managers, tails = [], []
    try:
        # One hot node + six empty spares, identical shape. 19 pods of
        # 2 CPU pile on `hot` (util .59 vs fleet mean .08): the
        # low-node-utilization strategy drains it toward the mean —
        # equilibrium leaves ~3 pods, so the wave is ~16 evictions, far
        # longer than the kill + takeover window at 4 evictions/s.
        for name in [HOT] + [f"s{i}" for i in range(6)]:
            node = (make_node().name(name)
                    .capacity({"cpu": 64, "memory": "256Gi", "pods": 110})
                    .obj())
            _any(urls, "POST", "/api/v1/nodes", node_to_wire(node))
        uids = []
        for i in range(16):
            uid = f"solo-{i:02d}"
            p = (make_pod().name(uid).uid(uid)
                 .labels({"app": uid}).req({"cpu": "2"}).obj())
            _any(urls, "POST", "/api/v1/pods", pod_to_wire(p))
            uids.append(uid)
        for uid in GANG:
            p = (make_pod().name(uid).uid(uid)
                 .labels({"app": uid}).req({"cpu": "2"}).obj())
            p.pod_group = "team"
            _any(urls, "POST", "/api/v1/pods", pod_to_wire(p))
            uids.append(uid)
        for uid in uids:
            _any(urls, "POST", f"/api/v1/pods/{uid}/binding",
                 {"node": HOT})

        repo, env = _repo_root(), _env()
        for i in range(2):
            proc, murl = start_descheduler(
                rs.follower_urls[0], repo, env, identity=f"dm-{i}",
                fallbacks=[rs.follower_urls[1], rs.leader_url],
                lease_ttl=LEASE, tick=0.1, hysteresis=1,
                primary_qps=4.0)
            managers.append((proc, murl))
            tails.append(drain_pipe(proc))

        _wait(lambda: _active_manager(managers) is not None,
              timeout=30, msg="an ACTIVE descheduler")
        _wait(lambda: _evictions_total(rs.leader_url) >= 3,
              timeout=30, msg="eviction wave under way")
        active_proc, _ = _active_manager(managers)
        active_proc.kill()  # SIGKILL: no lease release, no goodbye
        t_kill = time.monotonic()
        at_kill = _evictions_total(rs.leader_url)
        survivor = next((p, u) for p, u in managers
                        if p is not active_proc)

        _wait(lambda: _active_manager(managers) == survivor,
              timeout=LEASE * 8, msg="standby takeover")
        assert time.monotonic() - t_kill <= LEASE * 6  # inside TTL window

        # Quiesce: the counter stops moving for 3s straight AND the
        # survivor demonstrably continued the dead incumbent's wave.
        state = {"last": at_kill, "since": time.monotonic()}

        def _quiesced():
            now = _evictions_total(rs.leader_url)
            if now != state["last"]:
                state["last"], state["since"] = now, time.monotonic()
                return False
            return (now > at_kill
                    and time.monotonic() - state["since"] >= 3.0)
        _wait(_quiesced, timeout=90, msg="wave quiesce after takeover")
        final = _evictions_total(rs.leader_url)

        # Exactly-once: the census of evicted (pending, annotated) pods
        # IS the counter — nothing double-evicted, nothing lost.
        pods = {p["uid"]: p for p in _any(urls, "GET", "/api/v1/pods")}
        assert set(pods) == set(uids)  # eviction recreates, never drops
        evicted = {u for u, p in pods.items()
                   if not p.get("nodeName")
                   and (p.get("annotations") or {}).get(EVICTED_ANNOTATION)}
        assert len(evicted) == int(final) and len(evicted) > int(at_kill)

        # Gang-whole: never split at quiesce (here the gang's pods sort
        # first among equals, so the whole PodGroup moved).
        gang_evicted = {u for u in GANG if u in evicted}
        assert gang_evicted in (set(), set(GANG)), gang_evicted
        assert gang_evicted == set(GANG)

        # The ledger absorbs duplicates: replay every committed intent —
        # derived from NOTHING but (uid, node), exactly as the standby
        # re-derived them — and the counter must not move.
        replayed_before = _metric(_get_text(rs.leader_url, "/metrics"),
                                  "apiserver_pod_evictions_replayed_total")
        for uid in sorted(evicted):
            got = _any(urls, "POST", f"/api/v1/pods/{uid}/eviction",
                       {"intent": intent_for(uid, HOT), "node": HOT})
            assert got == {"evicted": True, "already": True}, (uid, got)
        end_text = _get_text(rs.leader_url, "/metrics")
        assert _metric(end_text, "apiserver_pod_evictions_total") == final
        assert (_metric(end_text, "apiserver_pod_evictions_replayed_total")
                - replayed_before) == len(evicted)

        stats = stop_controller(survivor[0],
                                tails[managers.index(survivor)])
        assert stats is not None
        assert stats["takeovers"] == 1 and stats["standby_ticks"] > 0
        # every intent the survivor planned is the deterministic one
        for uid, intent in stats["planned_intents"].items():
            assert intent == intent_for(uid, HOT), (uid, intent)
        assert stats["evictions_total"] >= 1  # it worked, not just held
    finally:
        for proc, _ in managers:
            if proc.poll() is None:
                proc.kill()
        rs.stop()
