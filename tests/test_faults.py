"""Chaos suite: fault injection against every resilience boundary
(docs/RESILIENCE.md).

Deterministic-seed tests carry the `chaos` marker and run in tier-1; the
long kill/restart stress is `slow` (excluded by `-m 'not slow'`).
"""

import json
import os
import socket
import threading
import time

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.api_dispatcher import (APICall, APIDispatcher,
                                                CALL_BINDING)
from kubernetes_tpu.core.backoff import (CircuitBreaker, RetryConfig,
                                         TransientAPIError, is_retriable,
                                         retry_call)
from kubernetes_tpu.core.clientset import RetryingClientset
from kubernetes_tpu.testing.faults import (ChaosTCPProxy, DeviceFaults,
                                           FlakyClientset)
from kubernetes_tpu.testing.wrappers import make_node, make_pod

FAST_RETRY = RetryConfig(initial_backoff=0.001, max_backoff=0.01,
                         max_attempts=4, seed=0)


def _nodes(n, cpu=16):
    return [make_node().name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 4}").obj() for i in range(n)]


def _pods(n, cpu="100m"):
    proto = (make_pod().name("proto").req({"cpu": cpu, "memory": "64Mi"})
             .labels({"app": "chaos"}).obj())
    return [proto.clone_from_template(f"p{i}") for i in range(n)]


# ---------------------------------------------------------------------------
# backoff.py units
# ---------------------------------------------------------------------------


class TestBackoff:
    def test_delays_deterministic_and_bounded(self):
        cfg = RetryConfig(initial_backoff=0.1, max_backoff=0.5,
                          multiplier=2.0, jitter=0.2, max_attempts=6, seed=7)
        a, b = list(cfg.delays()), list(cfg.delays())
        assert a == b  # same seed, same sequence
        assert len(a) == 5
        assert all(d <= 0.5 * 1.2 + 1e-9 for d in a)
        assert a[0] < a[-1]  # grows toward the cap

    def test_is_retriable_taxonomy(self):
        import http.client
        from urllib.error import HTTPError, URLError
        assert is_retriable(TransientAPIError("x"))
        assert is_retriable(ConnectionResetError())
        assert is_retriable(TimeoutError())
        assert is_retriable(socket.timeout())
        assert is_retriable(HTTPError("u", 503, "boom", {}, None))
        assert not is_retriable(HTTPError("u", 404, "nope", {}, None))
        assert is_retriable(URLError(ConnectionResetError()))
        assert is_retriable(http.client.RemoteDisconnected())
        assert not is_retriable(KeyError("pod not found"))
        assert not is_retriable(ValueError("bad spec"))

    def test_retry_call_replays_then_succeeds(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientAPIError("blip")
            return "ok"

        assert retry_call(flaky, FAST_RETRY, sleep=lambda d: None) == "ok"
        assert calls["n"] == 3

    def test_retry_call_nonretriable_raises_immediately(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise KeyError("missing")

        with pytest.raises(KeyError):
            retry_call(broken, FAST_RETRY, sleep=lambda d: None)
        assert calls["n"] == 1

    def test_retry_call_budget_exhausted(self):
        with pytest.raises(TransientAPIError):
            retry_call(lambda: (_ for _ in ()).throw(TransientAPIError("x")),
                       FAST_RETRY, sleep=lambda d: None)

    def test_circuit_breaker_lifecycle(self):
        t = {"now": 0.0}
        br = CircuitBreaker(failure_threshold=3, cooldown=5.0,
                            clock=lambda: t["now"])
        assert br.allows() and br.state == "closed"
        assert not br.record_failure()
        assert not br.record_failure()
        assert br.record_failure()  # third consecutive: opens
        assert br.state == "open" and not br.allows()
        t["now"] = 5.1
        assert br.state == "half-open" and br.allows()  # one probe
        assert br.record_failure()  # failed probe: re-opens
        assert not br.allows()
        t["now"] = 10.3
        assert br.allows()
        br.record_success()  # clean probe: closes
        assert br.state == "closed" and br.open_count == 2
        br.record_failure()
        br.record_success()  # success resets the consecutive count
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"


# ---------------------------------------------------------------------------
# clientset write retries
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestClientsetRetries:
    def test_write_retries_transparent(self):
        inner = FakeClientset()
        flaky = FlakyClientset(inner, fail_first={"create_pod": 2, "bind": 1})
        rcs = RetryingClientset(flaky, retry=FAST_RETRY)
        pod = _pods(1)[0]
        rcs.create_node(_nodes(1)[0])
        rcs.create_pod(pod)  # 2 injected faults, then lands
        assert pod.uid in inner.pods
        rcs.bind(pod, "n0")
        assert inner.bindings[pod.uid] == "n0"
        assert rcs.retries_total == 3
        assert flaky.injected == {"create_pod": 2, "bind": 1}
        assert rcs.give_ups == 0

    def test_semantic_error_not_retried(self):
        inner = FakeClientset()
        rcs = RetryingClientset(FlakyClientset(inner), retry=FAST_RETRY)
        with pytest.raises(KeyError):
            rcs.bind(_pods(1)[0], "n0")  # pod never created: not transient
        assert rcs.retries_total == 0

    def test_budget_exhaustion_propagates(self):
        inner = FakeClientset()
        flaky = FlakyClientset(inner, fail_first={"create_pod": 99})
        rcs = RetryingClientset(flaky, retry=FAST_RETRY)
        with pytest.raises(TransientAPIError):
            rcs.create_pod(_pods(1)[0])
        assert rcs.give_ups == 1
        assert rcs.retries_total == FAST_RETRY.max_attempts - 1

    def test_reads_and_registration_delegate(self):
        inner = FakeClientset()
        rcs = RetryingClientset(FlakyClientset(inner), retry=FAST_RETRY)
        seen = []
        rcs.on_pod_event(lambda kind, old, new: seen.append(kind))
        rcs.create_pod(_pods(1)[0])
        assert seen == ["add"]
        assert rcs.pods is inner.pods


# ---------------------------------------------------------------------------
# async API dispatcher retries
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestDispatcherRetries:
    def _flaky_call(self, fails, log):
        state = {"left": fails}

        def execute():
            if state["left"] > 0:
                state["left"] -= 1
                raise TransientAPIError("write timeout")
            log.append("done")

        return execute

    def test_inline_mode_retries_before_error(self):
        d = APIDispatcher(mode="inline", retry=FAST_RETRY)
        log = []
        d.add(APICall(CALL_BINDING, "u1", self._flaky_call(2, log)))
        assert log == ["done"]
        assert d.retried == 2 and d.executed == 1 and not d.errors

    def test_thread_mode_retries_then_inbox_on_exhaustion(self):
        d = APIDispatcher(mode="thread", retry=FAST_RETRY)
        try:
            log = []
            d.add(APICall(CALL_BINDING, "ok", self._flaky_call(3, log)))
            d.flush()
            assert log == ["done"] and not d.has_errors()
            failed = []
            d.add(APICall(CALL_BINDING, "doomed", self._flaky_call(99, []),
                          on_error=lambda e: failed.append(e)))
            d.flush()
            deadline = time.monotonic() + 5
            while not d.has_errors() and time.monotonic() < deadline:
                time.sleep(0.01)
            drained = d.drain_errors()
            assert len(drained) == 1  # only the budget-exhausted call
            assert isinstance(drained[0][1], TransientAPIError)
        finally:
            d.close()

    def test_semantic_error_skips_retry(self):
        d = APIDispatcher(mode="inline", retry=FAST_RETRY)
        errs = []
        d.add(APICall(CALL_BINDING, "u9",
                      lambda: (_ for _ in ()).throw(KeyError("pod gone")),
                      on_error=lambda e: errs.append(e)))
        assert d.retried == 0 and len(errs) == 1


# ---------------------------------------------------------------------------
# sidecar: disconnects, kill + restart, request replay
# ---------------------------------------------------------------------------


def _start_sidecar(path, max_batch=64):
    from kubernetes_tpu.parallel.sidecar import SidecarServer
    # mesh=None: the single-device kernel path — this environment's XLA
    # miscompiles the SPMD partitioning of the scan (pre-existing; the
    # breaker contains it), and chaos tests need a WORKING device path.
    server = SidecarServer(path, max_batch=max_batch, mesh=None)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.connect(path)
            probe.close()
            return server, t
        except OSError:
            time.sleep(0.02)
    raise TimeoutError("sidecar never came up")


@pytest.mark.chaos
def test_sidecar_survives_client_disconnects(tmp_path):
    from kubernetes_tpu.parallel.sidecar import SidecarClient
    path = str(tmp_path / "tpu.sock")
    server, _ = _start_sidecar(path)
    try:
        # A client that sends a truncated frame and vanishes...
        rude = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        rude.connect(path)
        rude.sendall(b"\x00\x00\x00\x10partial")
        rude.close()
        # ...and one that resets mid-exchange...
        rude2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        rude2.connect(path)
        rude2.sendall(b"\x00\x00")
        rude2.close()
        # ...must not take the server down.
        client = SidecarClient(path, timeout=10)
        assert client.ping()
        client.close()
        assert server.served_connections >= 3
    finally:
        server.shutdown()


def _oracle_assignments(nodes_fn, pods_fn):
    cs = FakeClientset()
    host = Scheduler(clientset=cs, deterministic_ties=True)
    for n in nodes_fn():
        cs.create_node(n)
    for p in pods_fn():
        cs.create_pod(p)
    host.run_until_idle()
    return {cs.pods[u].name: n for u, n in cs.bindings.items()}


def _run_sidecar_batches(tmp_path, n_nodes, n_pods, batch, kill_at=()):
    """Feed pods through the sidecar in batches, killing + restarting the
    server process-analogue before the batch indices in `kill_at`. Returns
    (assignments, client)."""
    from kubernetes_tpu.parallel.sidecar import SidecarClient
    path = str(tmp_path / "tpu.sock")
    server, _ = _start_sidecar(path)
    client = SidecarClient(
        path, timeout=60,
        retry=RetryConfig(initial_backoff=0.05, max_backoff=0.5,
                          max_attempts=10, seed=3))
    got = {}
    try:
        client.sync_nodes(_nodes(n_nodes))
        pods = _pods(n_pods)
        for bi in range(0, n_pods, batch):
            if bi // batch in kill_at:
                server.kill()  # SIGKILL analogue: no goodbye
                server, _ = _start_sidecar(path)
            chunk = pods[bi:bi + batch]
            assignments = client.schedule(chunk)
            for p, a in zip(chunk, assignments):
                got[p.name] = a
    finally:
        client.shutdown_server()
        client.close()
        server.shutdown()
    return got, client


@pytest.mark.chaos
def test_sidecar_kill_restart_replay(tmp_path):
    """One sidecar kill+restart mid-run (100 nodes / 1000 pods): the client
    reconnects, resyncs nodes + bound load + rotation, replays the lost
    request, and the full assignment map still matches a fault-free
    in-process oracle."""
    got, client = _run_sidecar_batches(
        tmp_path, n_nodes=100, n_pods=1000, batch=100, kill_at={3})
    oracle = _oracle_assignments(lambda: _nodes(100), lambda: _pods(1000))
    assert client.reconnects >= 1
    unassigned = [k for k, v in got.items() if not v]
    assert not unassigned, f"{len(unassigned)} pods unassigned"
    diffs = {k: (oracle.get(k), got[k]) for k in got if got[k] != oracle.get(k)}
    assert not diffs, f"{len(diffs)} divergences, e.g. {list(diffs.items())[:5]}"


@pytest.mark.slow
def test_sidecar_repeated_kill_stress(tmp_path):
    """Long-running kill/restart stress: three kills across a 1000-pod run."""
    got, client = _run_sidecar_batches(
        tmp_path, n_nodes=100, n_pods=1000, batch=50, kill_at={4, 9, 14})
    oracle = _oracle_assignments(lambda: _nodes(100), lambda: _pods(1000))
    assert client.reconnects >= 3
    assert {k: v for k, v in got.items() if v} == oracle


# ---------------------------------------------------------------------------
# device-path circuit breaker + the ADVICE r5 shape-error regression
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestDeviceBreaker:
    def test_preemption_never_interned_scalar_regression(self):
        """ADVICE r5 medium: a preemptor carrying a scalar resource the
        mirror never interned grows r_slots inside build_plan AFTER the
        victim tensors were built; the dry run must zero-pad and run, not
        crash the PostFilter cycle with a shape error."""
        from kubernetes_tpu.models import TPUScheduler
        cs = FakeClientset()
        sched = TPUScheduler(clientset=cs, max_batch=16, mesh=None)
        # Four node-level scalar resources fill the mirror's default
        # scalar tier exactly (s_cap=4): the NEXT interned scalar _grow()s.
        for i in range(4):
            cs.create_node(
                make_node().name(f"n{i}")
                .capacity({"cpu": 4, "memory": "8Gi", "pods": 110,
                           "r0.example.com/a": 8, "r1.example.com/b": 8,
                           "r2.example.com/c": 8, "r3.example.com/d": 8})
                .obj())
        for p in _pods(4, cpu="3"):  # victims: one 3-cpu pod per node
            cs.create_pod(p)
        sched.run_until_idle()
        assert len(cs.bindings) == 4
        r_slots_before = sched.mirror.r_slots
        pre = (make_pod().name("preemptor").priority(10)
               .req({"cpu": "2", "memory": "64Mi",
                     "ghost.example.com/widget": 1}).obj())
        fw = sched.framework_for_pod(pre)
        # Pre-fix this raised a shape error out of the kernel call.
        out = sched.device_dry_run_preemption(fw, None, pre, {}, 10, 0)
        assert sched.mirror.r_slots > r_slots_before  # the tier DID grow
        assert out is not None and out == []  # ghost resource: no candidate
        assert sched.preemption_device_evals == 1
        assert sched.device_breaker.state == "closed"
        # The fix handles it exactly — no fallback was needed.
        assert sched.metrics.device_path_fallback.value("RuntimeError") == 0

    def test_preemption_kernel_crash_falls_back_to_host(self):
        """The breaker backstop for the same class of failure: an injected
        kernel fault makes the dry run return None (host Evaluator owns the
        PostFilter), never a crash."""
        from kubernetes_tpu.models import TPUScheduler
        cs = FakeClientset()
        sched = TPUScheduler(clientset=cs, max_batch=16, mesh=None)
        for n in _nodes(4, cpu=4):
            cs.create_node(n)
        for p in _pods(4, cpu="3"):
            cs.create_pod(p)
        sched.run_until_idle()
        faults = DeviceFaults(preempt={1})
        sched._fault_hook = faults
        pre = (make_pod().name("pre").priority(10)
               .req({"cpu": "2", "memory": "64Mi"}).obj())
        fw = sched.framework_for_pod(pre)
        out = sched.device_dry_run_preemption(fw, None, pre, {}, 10, 0)
        assert out is None  # host path owns the dry run
        assert faults.injected["preempt"] == 1
        assert sched.metrics.device_path_fallback.value("RuntimeError") == 1
        assert sched.device_breaker.consecutive_failures == 1
        # Next call (fault cleared) succeeds and closes the count.
        sched._fault_hook = None
        out2 = sched.device_dry_run_preemption(fw, None, pre, {}, 10, 0)
        assert out2 is not None and len(out2) > 0
        assert sched.device_breaker.consecutive_failures == 0

    def test_session_crash_recovers_and_breaker_opens(self):
        """Every dispatch fails → sessions crash → stranded pods rerun on
        the host path, the breaker opens and pins the host path, and after
        the cool-down a clean probe closes it. All pods bind throughout."""
        from kubernetes_tpu.models import TPUScheduler
        cs = FakeClientset()
        sched = TPUScheduler(clientset=cs, max_batch=16, mesh=None)
        t = {"now": 0.0}
        sched.device_breaker = CircuitBreaker(
            failure_threshold=2, cooldown=5.0, clock=lambda: t["now"])
        for n in _nodes(8):
            cs.create_node(n)
        faults = DeviceFaults(dispatch=set(range(1, 100)))
        sched._fault_hook = faults
        for p in _pods(40):
            cs.create_pod(p)
        sched.run_until_idle()
        assert len(cs.bindings) == 40  # zero stranded pods, zero crashes
        assert sched.device_breaker.open_count >= 1
        assert not sched.device_breaker.allows()  # open: host path pinned
        assert sched.metrics.device_breaker_state.value() == 1.0
        fallbacks = sched.metrics.device_path_fallback.value("RuntimeError")
        assert fallbacks >= 2
        calls_while_open = faults.calls["dispatch"]
        for p in _pods(20):
            p.uid += "-b"  # fresh uids for a second wave
            cs.create_pod(p)
        sched.run_until_idle()
        assert len(cs.bindings) == 60
        assert faults.calls["dispatch"] == calls_while_open  # breaker held
        # Cool-down elapses; a clean probe session closes the breaker.
        t["now"] = 6.0
        sched._fault_hook = None
        for p in _pods(20):
            p.uid += "-c"
            cs.create_pod(p)
        sched.run_until_idle()
        assert len(cs.bindings) == 80
        assert sched.device_breaker.state == "closed"
        assert sched.metrics.device_breaker_state.value() == 0.0
        assert sched.device_scheduled > 0  # the device path came back


# ---------------------------------------------------------------------------
# watch re-list / resume over the wire
# ---------------------------------------------------------------------------


def _call_http(base, method, path, body=None):
    import json
    from urllib import request as urlrequest

    def once():
        from urllib.error import HTTPError
        data = json.dumps(body).encode() if body is not None else None
        req = urlrequest.Request(base + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
        try:
            with urlrequest.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except HTTPError as e:
            if e.code == 409:
                # AlreadyExists: an earlier attempt landed but its reply was
                # lost — the write is durable, which is all a retry wants.
                return {"conflict": True}
            raise

    # The test driver is an API client like any other: transient transport
    # failures against the loaded ThreadingHTTPServer (broken pipe under
    # thread churn) retry exactly as production clients do.
    return retry_call(once, RetryConfig(initial_backoff=0.05,
                                        max_backoff=0.5, max_attempts=6,
                                        seed=5))


class _Driver:
    """Run a scheduler loop on a thread, recording any crash."""

    def __init__(self, sched):
        self.sched = sched
        self.errors = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                if not self.sched.run_until_idle():
                    time.sleep(0.01)
            except Exception as e:  # noqa: BLE001 - the assertion target
                self.errors.append(e)
                return

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


@pytest.mark.chaos
def test_watch_drop_relist_convergence_mid_churn():
    """Kill every scheduler↔apiserver connection mid-MixedChurn: the
    reflector reconnects with its last resourceVersion, replays the missed
    events (RESUME), and assignments still match the in-process oracle."""
    from kubernetes_tpu.core.apiserver import (APIServer, HTTPClientset,
                                               node_to_wire, pod_to_wire)
    api = APIServer()
    port = api.serve(0)
    proxy = ChaosTCPProxy("127.0.0.1", port)
    direct = f"http://127.0.0.1:{port}"
    http_cs = HTTPClientset(proxy.url)
    rcs = RetryingClientset(http_cs, retry=RetryConfig(
        initial_backoff=0.005, max_backoff=0.1, max_attempts=6, seed=11))
    sched = Scheduler(clientset=rcs, deterministic_ties=True)
    driver = _Driver(sched)
    try:
        nodes = _nodes(20)
        for n in nodes:
            _call_http(direct, "POST", "/api/v1/nodes", node_to_wire(n))
        deadline = time.monotonic() + 30
        while len(http_cs.nodes) < 20 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(http_cs.nodes) == 20
        pods = _pods(300)
        for i, p in enumerate(pods):
            _call_http(direct, "POST", "/api/v1/pods", pod_to_wire(p))
            if i % 15 == 5:
                # churn irrelevant to scheduling outcomes (labels no plugin
                # reads) — pure watch traffic for the re-list to replay
                n = nodes[i % len(nodes)]
                w = node_to_wire(n)
                w["labels"]["churn"] = str(i)
                _call_http(direct, "PUT", f"/api/v1/nodes/{n.name}", w)
            if i == 150:
                proxy.drop_connections()  # watch streams die mid-churn
                for j in range(8):  # events the dead streams will miss
                    n = nodes[j]
                    w = node_to_wire(n)
                    w["labels"]["churn"] = f"offline-{j}"
                    _call_http(direct, "PUT", f"/api/v1/nodes/{n.name}", w)
        deadline = time.monotonic() + 120
        while len(api.store.bindings) < 300 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not driver.errors, f"scheduler crashed: {driver.errors!r}"
        bound = {api.store.pods[u].name: nn
                 for u, nn in api.store.bindings.items()}
        assert len(bound) == 300, f"only {len(bound)}/300 bound"
        oracle = _oracle_assignments(lambda: _nodes(20), lambda: _pods(300))
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences: {list(diffs.items())[:5]}"
        assert http_cs.resumes["pods"] + http_cs.resumes["nodes"] >= 1, \
            "reconnect never took the resourceVersion resume path"
    finally:
        driver.stop()
        http_cs.close()
        proxy.close()
        api.shutdown()


@pytest.mark.chaos
def test_chaos_end_to_end_100n_1000p():
    """The acceptance run: 100 nodes / 1000 pods over a real socket with
    (a) transient apiserver write failures, (b) a dropped watch stream
    mid-churn, and (c) injected device-path faults that trip and then
    clear the circuit breaker — assignments identical to a fault-free
    in-process oracle, zero scheduler crashes, breaker fired + recovered."""
    from kubernetes_tpu.core.apiserver import (APIServer, HTTPClientset,
                                               node_to_wire, pod_to_wire)
    from kubernetes_tpu.models import TPUScheduler
    api = APIServer()
    port = api.serve(0)
    proxy = ChaosTCPProxy("127.0.0.1", port)
    direct = f"http://127.0.0.1:{port}"
    http_cs = HTTPClientset(proxy.url)
    flaky = FlakyClientset(http_cs, seed=42, failure_rate=0.03)
    rcs = RetryingClientset(flaky, retry=RetryConfig(
        initial_backoff=0.005, max_backoff=0.05, max_attempts=5, seed=1))
    sched = TPUScheduler(clientset=rcs, max_batch=64, mesh=None)
    sched.device_breaker = CircuitBreaker(failure_threshold=3, cooldown=1.0)
    faults = DeviceFaults(dispatch={3, 4, 5})  # three consecutive crashes
    sched._fault_hook = faults
    driver = _Driver(sched)
    try:
        nodes = _nodes(100)
        for n in nodes:
            _call_http(direct, "POST", "/api/v1/nodes", node_to_wire(n))
        deadline = time.monotonic() + 60
        while len(http_cs.nodes) < 100 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(http_cs.nodes) == 100
        pods = _pods(1000)
        for i, p in enumerate(pods):
            _call_http(direct, "POST", "/api/v1/pods", pod_to_wire(p))
            if i % 25 == 10:  # outcome-irrelevant label churn
                n = nodes[i % len(nodes)]
                w = node_to_wire(n)
                w["labels"]["churn"] = str(i)
                _call_http(direct, "PUT", f"/api/v1/nodes/{n.name}", w)
            if i == 400:
                proxy.drop_connections()  # one dropped watch stream
                for j in range(10):
                    n = nodes[j]
                    w = node_to_wire(n)
                    w["labels"]["churn"] = f"offline-{j}"
                    _call_http(direct, "PUT", f"/api/v1/nodes/{n.name}", w)
        deadline = time.monotonic() + 300
        while len(api.store.bindings) < 1000 and time.monotonic() < deadline:
            time.sleep(0.1)
        # zero scheduler crashes
        assert not driver.errors, f"scheduler crashed: {driver.errors!r}"
        bound = {api.store.pods[u].name: nn
                 for u, nn in api.store.bindings.items()}
        assert len(bound) == 1000, f"only {len(bound)}/1000 bound"
        # assignments identical to the fault-free oracle
        oracle = _oracle_assignments(lambda: _nodes(100), lambda: _pods(1000))
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences: {list(diffs.items())[:5]}"
        # the write faults really fired and were retried away
        assert sum(flaky.injected.values()) > 0
        assert rcs.retries_total > 0 and rcs.give_ups == 0
        # the watch drop really resumed
        assert http_cs.resumes["pods"] + http_cs.resumes["nodes"] >= 1
        # the breaker fired and recovered
        assert faults.injected["dispatch"] == 3
        assert sched.metrics.device_path_fallback.value("RuntimeError") >= 3
        assert sched.device_breaker.open_count >= 1
        assert sched.device_breaker.allows()  # recovered (closed/half-open)
        assert sched.device_batches >= 1  # the device path did real work
    finally:
        driver.stop()
        http_cs.close()
        proxy.close()
        api.shutdown()


# ---------------------------------------------------------------------------
# apiserver kill -9 + WAL restart (PR-2 durability acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("wire_plane", ["binary", "json"])
def test_apiserver_kill9_restart_mixed_churn(tmp_path, monkeypatch,
                                             wire_plane):
    """The durability acceptance run: ``kill -9`` the apiserver OS process
    mid-MixedChurn, restart it in place from WAL+snapshot (same port, same
    data dir) — the reflector resumes on the PERSISTED epoch (RESUME, never
    a Replace re-list), zero bindings lost, zero duplicated, and terminal
    assignments identical to a no-fault in-process oracle."""
    from kubernetes_tpu.core.apiserver import (HTTPClientset, node_to_wire,
                                               pod_to_wire)
    from kubernetes_tpu.testing.faults import ApiServerProcess

    # Both wire planes (core/wire.py): binary is the negotiated default;
    # the json run pins the whole plane (WAL records, watch streams,
    # bodies) to the compat codec — the exactly-once/RESUME contract is
    # codec-independent. Subprocesses inherit the env.
    monkeypatch.setenv("TPU_SCHED_WIRE", wire_plane)
    N_PODS = 240
    # snapshot_every > total writes: this run recovers through pure WAL
    # replay, which keeps the recovered backlog covering the reflector's rv
    # deterministically (compaction+snapshot recovery is pinned by
    # tests/test_durability.py; a compaction racing the kill could
    # legitimately 410 the resume and flake the no-Replace assertion).
    api = ApiServerProcess(str(tmp_path / "apiserver-state"),
                           snapshot_every=100_000)
    http_cs = None
    driver = None
    try:
        http_cs = HTTPClientset(api.url)
        rcs = RetryingClientset(http_cs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=40, seed=13))
        sched = Scheduler(clientset=rcs, deterministic_ties=True)
        driver = _Driver(sched)
        nodes = _nodes(20)
        for n in nodes:
            _call_http(api.url, "POST", "/api/v1/nodes", node_to_wire(n))
        deadline = time.monotonic() + 30
        while len(http_cs.nodes) < 20 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(http_cs.nodes) == 20
        relists_before = dict(http_cs.relists)
        pods = _pods(N_PODS)
        for i, p in enumerate(pods):
            _call_http(api.url, "POST", "/api/v1/pods", pod_to_wire(p))
            if i % 15 == 5:
                # outcome-irrelevant node churn: pure watch traffic the
                # recovered backlog must replay across the restart
                n = nodes[i % len(nodes)]
                w = node_to_wire(n)
                w["labels"]["churn"] = str(i)
                _call_http(api.url, "PUT", f"/api/v1/nodes/{n.name}", w)
            if i == N_PODS // 2:
                api.kill9()    # SIGKILL mid-flight: in-flight binds die raw
                api.restart()  # recover WAL on the same port
        deadline = time.monotonic() + 120
        got = []
        while time.monotonic() < deadline:
            got = _call_http(api.url, "GET", "/api/v1/pods")
            if sum(1 for p in got if p["nodeName"]) >= N_PODS:
                break
            time.sleep(0.1)
        assert not driver.errors, f"scheduler crashed: {driver.errors!r}"
        bound = {p["name"]: p["nodeName"] for p in got if p["nodeName"]}
        # zero lost bindings (pre-crash binds recovered from the WAL,
        # in-flight ones replayed by the retry layer)...
        assert len(bound) == N_PODS, f"only {len(bound)}/{N_PODS} bound"
        # ...and zero duplicates: one store object per pod, one binding
        # each (a conflicting rebind 409s server-side and would have
        # surfaced in driver.errors).
        names = [p["name"] for p in got]
        assert len(names) == len(set(names)) == N_PODS
        oracle = _oracle_assignments(lambda: _nodes(20),
                                     lambda: _pods(N_PODS))
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences: {list(diffs.items())[:5]}"
        # the kill really happened, and the reflector rode the persisted
        # epoch straight through: RESUME on reconnect, never a Replace
        assert api.kills == 1 and api.restarts == 1
        assert http_cs.resumes["pods"] + http_cs.resumes["nodes"] >= 1
        assert dict(http_cs.relists) == relists_before
        # Flight recorder (core/spans.py): the chaos kill leaves forensic
        # artifacts in the data dir instead of nothing — the SIGKILLed
        # process's periodic dumps and/or the restarted process's dumps
        # (its graceful stop below guarantees a shutdown dump). Every
        # artifact parses line-by-line and leads with a meta row.
        api.stop()  # graceful: SIGTERM → shutdown dump (idempotent w/ finally)
        art_dir = str(tmp_path / "apiserver-state")
        arts = [f for f in os.listdir(art_dir)
                if f.startswith("flightrec-") and f.endswith(".jsonl")]
        assert arts, "apiserver chaos run left no flight-recorder artifact"
        for name in arts:
            with open(os.path.join(art_dir, name)) as f:
                rows = [json.loads(line) for line in f if line.strip()]
            assert rows and rows[0]["kind"] == "meta"
            assert rows[0]["proc"] == "apiserver"
    finally:
        if driver is not None:
            driver.stop()
        if http_cs is not None:
            http_cs.close()
        api.stop()


# ---------------------------------------------------------------------------
# shard-kill failover (PR-5 shard plane acceptance; docs/SHARDING.md)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("wire_plane", [
    "binary", pytest.param("json", marks=pytest.mark.slow)])
def test_shard_kill_adoption_mixed_churn(tmp_path, monkeypatch, wire_plane):
    """SIGKILL one of 3 shard scheduler PROCESSES mid-MixedChurn: its lease
    ages past expiry unrenewed, the ring successor adopts the dead range
    (sweeping the informer backlog the dead shard never drained), and the
    run still binds every pod exactly once — zero lost, zero duplicated.
    Failover needs no handoff protocol: adoption is recomputed from the
    server-evaluated lease table, and any transient overlap resolves
    through the binding subresource's 409s."""
    from kubernetes_tpu.shard.harness import _call, run_sharded_cluster

    # Wire-plane parameterization: binary (the negotiated default) in
    # tier-1, the json compat plane in the slow tier — adoption and
    # exactly-once must hold identically on both.
    monkeypatch.setenv("TPU_SCHED_WIRE", wire_plane)
    LEASE = 2.0
    state = {"killed_at": 0.0, "nodes": None, "churn": 0}

    def cb(bound, cluster):
        if state["nodes"] is None:
            state["nodes"] = _call(cluster.base, "GET", "/api/v1/nodes")
        if not cluster.killed:
            # Kill at the FIRST progress poll: bulk binding commits drain a
            # 240-pod backlog within ~2 polls, so any bound-count trigger
            # fires after the victim already finished its range and the
            # failover would have nothing to adopt. At poll one the pods
            # are created but shard 1's range is still (mostly) pending —
            # the range MUST drain through lease expiry + adoption.
            cluster.kill(1)  # SIGKILL: no goodbye, lease left to expire
            state["killed_at"] = time.monotonic()
        # outcome-irrelevant label churn on every poll: live watch traffic
        # the survivors keep classifying while the failover runs
        state["churn"] += 1
        w = dict(state["nodes"][state["churn"] % len(state["nodes"])])
        w["labels"] = dict(w.get("labels") or {}, churn=str(state["churn"]))
        _call(cluster.base, "PUT", f"/api/v1/nodes/{w['name']}", w)

    flightrec_dir = str(tmp_path / "flightrec")
    out = run_sharded_cluster(
        3, 40, 240, lease_duration=LEASE, warm_pods=24,
        progress_cb=cb, timeout=420.0, flightrec_dir=flightrec_dir)
    assert out["killed_shards"] == [1]
    # zero lost bindings: the dead shard's range drained through adoption
    assert out["all_bound"], f"lost bindings: {out}"
    # zero duplicates: one store object per pod, one node each
    assert out["distinct_bound_pods"] == 240 + 24
    # the failover demonstrably ran: a survivor adopted ≥1 expired range
    # and the two survivors ended up owning all 3 slots between them
    survivors = out["shard_metrics"]
    assert sum(m.get("scheduler_shard_adoptions_total", 0)
               for m in survivors) >= 1, survivors
    assert sum(m.get("scheduler_shard_owned_shards", 0)
               for m in survivors) >= 3, survivors
    assert state["killed_at"] > 0  # the kill actually fired mid-run
    # Flight recorder (core/spans.py): the chaos kill leaves forensic
    # artifacts — the SIGKILLed member's periodic dumps survive on disk,
    # the survivors dump at shutdown, and the ADOPTER's artifact carries
    # the 100%-sampled shard.adopt span marking the failover instant.
    arts = [f for f in os.listdir(flightrec_dir)
            if f.startswith("flightrec-") and f.endswith(".jsonl")]
    assert len(arts) >= 3, f"expected artifacts from ≥3 processes: {arts}"
    adopt_spans = []
    for name in arts:
        with open(os.path.join(flightrec_dir, name)) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        assert rows and rows[0]["kind"] == "meta"
        adopt_spans += [r for r in rows
                        if r.get("kind") == "span"
                        and r.get("name") == "shard.adopt"]
    assert adopt_spans, "no shard.adopt span in any flight-recorder artifact"
    assert adopt_spans[0]["attrs"]["shards"]


# ---------------------------------------------------------------------------
# replicated control plane: leader/follower kill -9
# (kubernetes_tpu/replication/; docs/RESILIENCE.md § replication)
# ---------------------------------------------------------------------------


def _wait_true(cond, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _flight_spans(flight_dir, name):
    spans = []
    for fname in os.listdir(flight_dir):
        if not (fname.startswith("flightrec-") and fname.endswith(".jsonl")):
            continue
        with open(os.path.join(flight_dir, fname)) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        assert rows and rows[0]["kind"] == "meta"
        spans += [r for r in rows
                  if r.get("kind") == "span" and r.get("name") == name]
    return spans


@pytest.mark.chaos
@pytest.mark.parametrize("wire_plane", [
    "binary", pytest.param("json", marks=pytest.mark.slow)])
def test_leader_kill9_promotion_mixed_churn(tmp_path, monkeypatch,
                                            wire_plane):
    """The replication acceptance run: ``kill -9`` the LEADER apiserver
    mid-MixedChurn with TWO shard schedulers reading from two followers.
    The lowest-ranked live follower promotes within the lease TTL (fenced
    by the epoch bump), the shards' follower-served watch streams never
    re-list (no 410), every pod binds exactly once, and the terminal
    assignments match the oracle — pods are node-selector-pinned, so the
    expected placement is interleaving-independent and any lost/replayed/
    misrouted bind shows up as a divergence."""
    from kubernetes_tpu.core.apiserver import (HTTPClientset, node_from_wire,
                                               node_to_wire)
    from kubernetes_tpu.shard import ShardMember
    from kubernetes_tpu.testing.faults import ReplicaSet

    # Wire-plane parameterization (core/wire.py): the binary run is the
    # negotiated default (tier-1); the json run rides the slow tier and
    # proves promotion/exactly-once are codec-independent.
    monkeypatch.setenv("TPU_SCHED_WIRE", wire_plane)
    N_PODS, N_NODES, LEASE = 240, 20, 2.0
    flight = str(tmp_path / "flightrec")
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=LEASE, flightrec_dir=flight)
    members, drivers, clients = [], [], []
    try:
        for i in range(2):
            base = rs.follower_urls[i]
            fb = [u for u in rs.follower_urls if u != base] + [rs.leader_url]
            http_cs = HTTPClientset(base, fallbacks=fb)
            clients.append(http_cs)
            rcs = RetryingClientset(http_cs, retry=RetryConfig(
                initial_backoff=0.05, max_backoff=0.5, max_attempts=40,
                seed=17 + i))
            sched = Scheduler(clientset=rcs, deterministic_ties=True)
            # Generous shard leases: the failover under test is the CONTROL
            # PLANE's; shard ranges must not flap around it.
            member = ShardMember(sched, i, 2, lease_duration=30.0,
                                 identity=f"chaos-shard-{i}")
            member.start_renewer()
            members.append(member)
            drivers.append(_Driver(sched))
        # The create/churn driver is an API client like any other — and it
        # rides the same NotLeader/re-resolve protocol across the kill.
        wcs = HTTPClientset(rs.follower_urls[0],
                            fallbacks=[rs.follower_urls[1]])
        clients.append(wcs)
        writer = RetryingClientset(wcs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=40, seed=99))
        nodes = [make_node().name(f"n{i}")
                 .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                 .label("slot", str(i)).obj() for i in range(N_NODES)]
        for n in nodes:
            writer.create_node(n)
        for cs in clients[:2]:
            assert _wait_true(lambda cs=cs: len(cs.nodes) == N_NODES)
        relists0 = [dict(cs.relists) for cs in clients[:2]]
        pods = [make_pod().name(f"p{i}")
                .req({"cpu": "100m", "memory": "64Mi"})
                .node_selector({"slot": str(i % N_NODES)}).obj()
                for i in range(N_PODS)]
        t_promoted = None
        for i, p in enumerate(pods):
            writer.create_pod(p)
            if i % 15 == 5:
                # outcome-irrelevant node churn: live watch traffic the
                # follower streams keep fanning out through the failover
                w = node_to_wire(nodes[i % N_NODES])
                w["labels"] = dict(w["labels"], churn=str(i))
                writer.update_node(node_from_wire(w))
            if i == N_PODS // 2:
                rs.kill9_leader()  # SIGKILL: no flush, no goodbye
                t_kill = time.monotonic()
                new_leader = rs.wait_for_leader(timeout=LEASE * 5)
                t_promoted = time.monotonic() - t_kill
                # The lowest-ranked live follower took over...
                assert new_leader == rs.follower_urls[0], new_leader
                # ...inside the failover budget: one lease TTL of silence
                # to detect, then probe + promote.
                assert t_promoted < LEASE * 2.5, t_promoted
        # drain: every measured pod bound, observed via FOLLOWER reads
        assert _wait_true(
            lambda: _call_http(rs.follower_urls[1], "GET",
                               "/api/v1/pods?summary=true")["bound"]
            >= N_PODS, timeout=120)
        for d in drivers:
            assert not d.errors, f"scheduler crashed: {d.errors!r}"
        got = _call_http(rs.follower_urls[0], "GET", "/api/v1/pods")
        bound = {p["name"]: p["nodeName"] for p in got if p["nodeName"]}
        # zero lost bindings, zero duplicates
        assert len(bound) == N_PODS, f"only {len(bound)}/{N_PODS} bound"
        names = [p["name"] for p in got]
        assert len(names) == len(set(names)) == N_PODS
        # oracle-identical assignments (selector-pinned placement)
        oracle = {f"p{i}": f"n{i % N_NODES}" for i in range(N_PODS)}
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences: {list(diffs.items())[:5]}"
        # follower-served reads NEVER re-listed across the failover window
        for cs, before in zip(clients[:2], relists0):
            assert dict(cs.relists) == before
            assert cs.failover_count >= 1
        # the promotion is fenced: the new leader runs epoch 2
        st = rs.status(rs.follower_urls[0])
        assert st["role"] == "leader" and st["replEpoch"] >= 2
        # forensics: the promoted follower's flight-recorder artifact
        # carries the 100%-sampled replication.promote span
        promote_spans = _flight_spans(flight, "replication.promote")
        assert promote_spans, "no replication.promote span in any artifact"
        assert promote_spans[0]["attrs"]["epoch"] >= 2
        assert promote_spans[0]["proc"] == "apiserver-r1"
    finally:
        for m in members:
            m.stop()
        for d in drivers:
            d.stop()
        for cs in clients:
            cs.close()
        rs.stop()


@pytest.mark.chaos
def test_follower_kill9_read_plane_failover(tmp_path):
    """``kill -9`` a FOLLOWER mid-MixedChurn: the scheduler reading from it
    rotates its reflector to a sibling replica and RESUMEs from the shared
    rv/epoch space (no re-list, stall bounded by a few connect backoffs),
    the run binds every pod exactly once, and assignments still match the
    no-fault in-process oracle."""
    from kubernetes_tpu.core.apiserver import (HTTPClientset, node_to_wire,
                                               pod_to_wire)
    from kubernetes_tpu.testing.faults import ReplicaSet

    N_PODS, N_NODES = 160, 20
    flight = str(tmp_path / "flightrec")
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=2.0, flightrec_dir=flight)
    http_cs = None
    driver = None
    try:
        http_cs = HTTPClientset(
            rs.follower_urls[0],
            fallbacks=[rs.follower_urls[1], rs.leader_url])
        rcs = RetryingClientset(http_cs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=40, seed=23))
        sched = Scheduler(clientset=rcs, deterministic_ties=True)
        driver = _Driver(sched)
        nodes = _nodes(N_NODES)
        for n in nodes:
            _call_http(rs.leader_url, "POST", "/api/v1/nodes",
                       node_to_wire(n))
        assert _wait_true(lambda: len(http_cs.nodes) == N_NODES)
        relists0 = dict(http_cs.relists)
        pods = _pods(N_PODS)
        t_kill = None
        for i, p in enumerate(pods):
            _call_http(rs.leader_url, "POST", "/api/v1/pods", pod_to_wire(p))
            if i % 15 == 5:
                n = nodes[i % N_NODES]
                w = node_to_wire(n)
                w["labels"]["churn"] = str(i)
                _call_http(rs.leader_url, "PUT", f"/api/v1/nodes/{n.name}", w)
            if i == N_PODS // 2:
                rs.kill9_follower(0)  # the scheduler's read replica dies
                t_kill = time.monotonic()
        assert _wait_true(
            lambda: _call_http(rs.leader_url, "GET",
                               "/api/v1/pods?summary=true")["bound"]
            >= N_PODS, timeout=120)
        assert not driver.errors, f"scheduler crashed: {driver.errors!r}"
        assert t_kill is not None
        got = _call_http(rs.leader_url, "GET", "/api/v1/pods")
        bound = {p["name"]: p["nodeName"] for p in got if p["nodeName"]}
        assert len(bound) == N_PODS, f"only {len(bound)}/{N_PODS} bound"
        names = [p["name"] for p in got]
        assert len(names) == len(set(names)) == N_PODS
        oracle = _oracle_assignments(lambda: _nodes(N_NODES),
                                     lambda: _pods(N_PODS))
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences: {list(diffs.items())[:5]}"
        # the read plane failed over by ROTATION + RESUME, never a re-list
        assert http_cs.read_rotations >= 1
        assert dict(http_cs.relists) == relists0
        assert (http_cs.resumes["pods"] + http_cs.resumes["nodes"]) >= 1
        # forensics: graceful stop (SIGTERM -> shutdown dump; idempotent
        # with the finally) guarantees survivor artifacts, and a run that
        # outlives the periodic timer leaves the SIGKILLed follower's too
        rs.stop()
        arts = [f for f in os.listdir(flight)
                if f.startswith("flightrec-") and f.endswith(".jsonl")]
        assert arts, "follower chaos run left no flight-recorder artifact"
    finally:
        if driver is not None:
            driver.stop()
        if http_cs is not None:
            http_cs.close()
        rs.stop()


# ---------------------------------------------------------------------------
# overload plane: flood shedding, preemption storms, failover under flood
# (core/flowcontrol.py; docs/RESILIENCE.md § overload & fairness)
# ---------------------------------------------------------------------------


def _p99_of_window(hist, before_counts):
    """p99 over the observations a histogram gained SINCE `before_counts`
    (a snapshot of its unlabeled per-bucket counts): bucket-diff fed back
    through the same interpolation — per-phase latency truth without
    per-pod timestamps."""
    from kubernetes_tpu.core.metrics import Histogram

    after = list(hist._counts.get((), [0] * (len(hist.buckets) + 1)))
    diff = [a - b for a, b in zip(after, before_counts)]
    h = Histogram("window", "", buckets=hist.buckets)
    h._counts[()] = diff
    h._totals[()] = sum(diff)
    return h.percentile(0.99)


def _hist_counts(hist):
    return list(hist._counts.get((), [0] * (len(hist.buckets) + 1)))


def _pick_flood_namespace(avoid_flows, queues, hand_size):
    """A flood namespace whose shuffle-shard hand shares no queue with the
    well-behaved flows' hands — the isolation the test then PROVES held."""
    from kubernetes_tpu.core.flowcontrol import WORKLOAD, shuffle_shard_hand

    taken = set()
    for flow in avoid_flows:
        taken |= set(shuffle_shard_hand(WORKLOAD, flow, queues, hand_size))
    for i in range(256):
        ns = f"flood-{i}"
        if not (set(shuffle_shard_hand(WORKLOAD, ns, queues, hand_size))
                & taken):
            return ns
    raise AssertionError("no isolated flood namespace found")


@pytest.mark.chaos
def test_adversarial_tenant_flood_fairness(tmp_path, monkeypatch):
    """Scenario 1 of the overload pack: one adversarial tenant hammers
    creates while two well-behaved namespaces keep scheduling. The flood
    is SHED at 429 (every shed carrying Retry-After), the well-behaved
    tenants' p99 e2e latency stays within 2x their unloaded baseline,
    every well-behaved pod binds exactly once oracle-identically, and the
    scheduler's fair dequeue keeps serving both tenants.

    The plane is a REAL replicated pair (leader + follower OS processes):
    reply gating holds each write's admission seat across the ship-ack
    round trip, so concurrent requests genuinely contend at the gate.
    (In-process, the whole admit->write->release window runs without a
    blocking point and the GIL serializes handlers straight through it —
    shedding then hinges on preemption luck, not on load.)"""
    import http.client as _hc
    from urllib.parse import urlsplit

    from kubernetes_tpu.core.apiserver import (HTTPClientset, node_to_wire,
                                               pod_to_wire)
    from kubernetes_tpu.core.config import SchedulerConfiguration
    from kubernetes_tpu.core import wire as _wire
    from kubernetes_tpu.shard.harness import scrape_labeled
    from kubernetes_tpu.testing.faults import ReplicaSet

    N_NODES, PER_NS = 12, 24
    # A deliberately tight workload lane (env seam — the spawned
    # apiservers take no constructor args) so the 16-thread flood
    # saturates it: 2 seats, 4 queues of 2, 1-wide hands, 0.25s max_wait.
    # Exempt/system stay stock — nothing can make the exempt lane shed.
    monkeypatch.setenv("TPU_SCHED_APF_WORKLOAD", "2,4,2,1,0.25")
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=1, repl_lease=5.0)
    base = rs.leader_url
    host, _, port = urlsplit(base).netloc.partition(":")
    port = int(port)
    flood_ns = _pick_flood_namespace(["web", "batch"], queues=4, hand_size=1)
    http_cs = HTTPClientset(base)
    rcs = RetryingClientset(http_cs, retry=RetryConfig(
        initial_backoff=0.02, max_backoff=0.5, max_attempts=40, seed=5,
        retry_after_cap=1.0))
    sched = Scheduler(clientset=rcs, deterministic_ties=True,
                      config=SchedulerConfiguration(fair_tenant_dequeue=True))
    driver = _Driver(sched)
    flood_stop = threading.Event()
    flood_stats = []  # per-worker dicts (no racy shared increments)

    def flood_worker(widx):
        # BULK creates, deleted right back (the same create/delete churn
        # hammer the sharded flood uses): each accepted bulk holds its
        # admission seat across store+WAL+fanout AND the replication
        # ship-ack gate, so the other workers' requests pile up behind it
        # and shed — while the delete-back keeps the store and the
        # scheduler's unschedulable pool from accumulating the flood.
        stats = {"shed": 0, "posted": 0, "bad_envelope": 0}
        flood_stats.append(stats)
        conn = _hc.HTTPConnection(host, port, timeout=30)
        seq = 0
        proto = (make_pod().name("proto").namespace(flood_ns)
                 .req({"cpu": "4096", "memory": "1Gi"}).obj())

        def rt(method, path, body=None):
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            resp.read()
            if resp.status == 429:
                stats["shed"] += 1
                if resp.getheader("Retry-After") is None:
                    stats["bad_envelope"] += 1  # broken shed contract
                return None
            return resp.status

        while not flood_stop.is_set():
            seq += 1
            pods = [proto.clone_from_template(f"fl-{widx}-{seq}-{i}")
                    for i in range(24)]
            try:
                if rt("POST", "/api/v1/pods", _wire.jdumps(
                        [pod_to_wire(p) for p in pods]).encode()) is None:
                    flood_stop.wait(0.05)  # shed: even adversaries pause
                    continue
                stats["posted"] += 1
                for p in pods:
                    # best-effort delete-back; a shed delete just retries
                    # next round — the residue stays bounded.
                    for _ in range(3):
                        if rt("DELETE", f"/api/v1/pods/{p.uid}") is not None:
                            break
                        flood_stop.wait(0.02)
            except (OSError, _hc.HTTPException):
                conn.close()
                conn = _hc.HTTPConnection(host, port, timeout=30)
        conn.close()

    try:
        for i in range(N_NODES):
            _call_http(base, "POST", "/api/v1/nodes", node_to_wire(
                make_node().name(f"n{i}")
                .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                .label("slot", str(i)).obj()))
        assert _wait_true(lambda: len(http_cs.nodes) == N_NODES)

        def make_tenant_pods(phase):
            out = []
            for ns in ("web", "batch"):
                for i in range(PER_NS):
                    out.append(make_pod().name(f"{ns}-{phase}-{i}")
                               .namespace(ns)
                               .req({"cpu": "100m", "memory": "64Mi"})
                               .node_selector({"slot": str(i % N_NODES)})
                               .obj())
            return out

        def bound_count():
            s = _call_http(base, "GET", "/api/v1/pods?summary=true")
            return s["bound"]

        # Phase A — unloaded baseline.
        e2e = sched.metrics.e2e_scheduling_duration
        snap0 = _hist_counts(e2e)
        for p in make_tenant_pods("a"):
            _call_http(base, "POST", "/api/v1/pods", pod_to_wire(p))
        assert _wait_true(lambda: bound_count() >= 2 * PER_NS, timeout=60)
        p99_base = _p99_of_window(e2e, snap0)

        # Phase B — the same well-behaved load, under a 16-thread flood.
        snap1 = _hist_counts(e2e)
        threads = [threading.Thread(target=flood_worker, args=(w,),
                                    daemon=True) for w in range(16)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # flood saturates its lane first
        for p in make_tenant_pods("b"):
            rcs.create_pod(p)  # Retry-After-honoring writer
        assert _wait_true(lambda: bound_count() >= 4 * PER_NS, timeout=120)
        p99_flood = _p99_of_window(e2e, snap1)
        flood_stop.set()
        for t in threads:
            t.join(timeout=30)

        # The flood really was shed, with the full envelope, every time —
        # and the exempt lane (the replication control traffic that kept
        # the follower in quorum throughout) was never queued or shed.
        shed = sum(s["shed"] for s in flood_stats)
        rejected = scrape_labeled(base, "apiserver_flowcontrol_rejected_total",
                                  "priority_level")
        queued = scrape_labeled(base, "apiserver_flowcontrol_queued_total",
                                "priority_level")
        assert shed > 0, (flood_stats, rejected, queued)
        assert sum(s["bad_envelope"] for s in flood_stats) == 0
        assert rejected.get("workload", 0) >= shed
        assert rejected.get("exempt", 0) == 0
        assert queued.get("exempt", 0) == 0
        # Well-behaved tenants: all bound, exactly once, oracle-identical.
        got = _call_http(base, "GET", "/api/v1/pods")
        tenant = [p for p in got if p["namespace"] in ("web", "batch")]
        assert len(tenant) == 4 * PER_NS
        assert all(p["nodeName"] for p in tenant)
        names = [p["name"] for p in tenant]
        assert len(names) == len(set(names))
        for p in tenant:
            slot = p["name"].rsplit("-", 1)[1]
            assert p["nodeName"] == f"n{int(slot) % N_NODES}", p
        # Bounded degradation: within 2x the unloaded p99 (+1 bucket of
        # slack for the 2-core box's scheduling noise).
        assert p99_flood <= 2.0 * p99_base + 1.0, (p99_base, p99_flood)
        # Fair dequeue engaged and served both well-behaved tenants; the
        # flood pods that landed popped too (into the unschedulable pool —
        # cpu 4096 fits nowhere) instead of monopolizing the queue.
        assert sched.queue.fair_tenant_dequeue
        pops = sched.queue.active_q.pops
        assert pops.get("web", 0) >= PER_NS
        assert pops.get("batch", 0) >= PER_NS
        assert not driver.errors, driver.errors
        # Starvation gauge renders per-namespace (flood pods pending).
        assert "scheduler_queue_starvation_seconds" in sched.metrics.expose()
    finally:
        flood_stop.set()
        driver.stop()
        http_cs.close()
        rs.stop()


class _CountingClientset:
    """Clientset decorator counting delete_pod calls per uid — the
    exactly-once-victim probe for preemption storms."""

    def __init__(self, inner):
        self._inner = inner
        self.deletes = {}

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name == "delete_pod":
            def counted(pod, _attr=attr):
                self.deletes[pod.uid] = self.deletes.get(pod.uid, 0) + 1
                return _attr(pod)
            return counted
        return attr


def _run_gang_storm():
    """One full gangs-preempting-gangs storm on the in-process plane;
    returns (final placements by name, per-uid delete counts, scheduler)."""
    from kubernetes_tpu.api.types import PodGroup
    from kubernetes_tpu.core.registry import gang_placement_profiles

    cs = _CountingClientset(FakeClientset())
    names = {}  # uid -> name (uids are globally sequenced across runs)
    s = Scheduler(clientset=cs, profile_factory=gang_placement_profiles,
                  deterministic_ties=True)
    for i in range(10):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                       .zone(f"z{i % 2}").obj())
    # Fill tier: 10 low-priority gangs of 2 — the cluster is exactly full.
    for g in range(10):
        cs.create_pod_group(PodGroup(name=f"fill-{g}", min_count=2))
        for i in range(2):
            p = (make_pod().name(f"fill-{g}-{i}").req({"cpu": "4"})
                 .priority(1).obj())
            p.pod_group = f"fill-{g}"
            names[p.uid] = p.name
            cs.create_pod(p)
    s.run_until_idle()
    assert len(cs.bindings) == 20, "fill tier must saturate the cluster"
    # Storm: 5 high-priority gangs and 5 mid-priority singles arrive
    # together over the full cluster — gangs preempt gangs.
    for g in range(5):
        cs.create_pod_group(PodGroup(name=f"storm-{g}", min_count=2))
        for i in range(2):
            p = (make_pod().name(f"storm-{g}-{i}").req({"cpu": "4"})
                 .priority(100).obj())
            p.pod_group = f"storm-{g}"
            cs.create_pod(p)
    for i in range(5):
        cs.create_pod(make_pod().name(f"mid-{i}").req({"cpu": "4"})
                      .priority(50).obj())
    for _ in range(50):
        s.run_until_idle()
        s.process_async_api_errors()
        storm = [p for p in cs.pods.values()
                 if p.name.startswith(("storm-", "mid-"))]
        if len(storm) == 15 and all(p.node_name for p in storm):
            break
        time.sleep(0.01)
    placements = {p.name: p.node_name for p in cs.pods.values()}
    deletes_by_name = {names.get(uid, uid): c
                       for uid, c in cs.deletes.items()}
    return placements, deletes_by_name, s


@pytest.mark.chaos
def test_preemption_storm_gangs_exactly_once_victims():
    """Scenario 2a: priority tiers over a FULL cluster, gangs preempting
    gangs — every storm pod lands, every victim is deleted EXACTLY once
    (never re-deleted by a second cycle racing the first's async victim
    deletion), no node ends overcommitted, and the whole storm is
    deterministic (two identical runs, identical placements)."""
    placements, deletes, s = _run_gang_storm()
    storm = {n: node for n, node in placements.items()
             if n.startswith(("storm-", "mid-"))}
    assert len(storm) == 15 and all(storm.values()), storm
    # Exactly-once victims: every deleted fill pod deleted once, and gone.
    assert deletes, "the storm preempted nobody"
    assert all(c == 1 for c in deletes.values()), deletes
    fills_left = [n for n in placements if n.startswith("fill-")]
    # Storm demand = 15 pods x 4 cpu over 10x8 cpu: exactly 15 victims.
    assert len(deletes) == 15 and len(fills_left) == 5
    # No node overcommitted: cpu 8 holds at most 2 of these 4-cpu pods.
    per_node = {}
    for name, node in placements.items():
        per_node[node] = per_node.get(node, 0) + 1
    assert all(c <= 2 for c in per_node.values()), per_node
    # Gang atomicity: each storm gang's members are both placed.
    for g in range(5):
        assert placements[f"storm-{g}-0"] and placements[f"storm-{g}-1"]
    # The async victim-deletion path really ran, successfully.
    assert s.metrics.preemption_goroutines_execution_total.value(
        "success") >= 1
    # Determinism (the in-process oracle property): identical rerun,
    # identical terminal placements and victim set.
    placements2, deletes2, _s2 = _run_gang_storm()
    assert placements2 == placements
    assert set(deletes2) == set(deletes)


@pytest.mark.chaos
def test_preemption_storm_sharded_exactly_once_victims():
    """Scenario 2b: the storm's shard half — 2 shard schedulers over a
    REAL apiserver, high-priority pinned preemptors arriving over a full
    cluster. Victims are deleted exactly once (asserted from a watcher's
    DELETED event counts — a double delete would fan out twice), the
    preemptors land oracle-identically on their pinned nodes, and the
    optimistic bind plane stays overcommit-free under shard conflicts."""
    from kubernetes_tpu.core.apiserver import (APIServer, HTTPClientset,
                                               node_to_wire, pod_to_wire)
    from kubernetes_tpu.shard.plane import ShardPlane

    N_NODES = 10
    api = APIServer()
    port = api.serve(0)
    base = f"http://127.0.0.1:{port}"

    def factory(cs):
        return Scheduler(clientset=cs, deterministic_ties=True)

    plane = ShardPlane(base, 2, lease_duration=30.0,
                       scheduler_factory=factory)
    observer = None
    deleted_counts = {}
    try:
        for i in range(N_NODES):
            _call_http(base, "POST", "/api/v1/nodes", node_to_wire(
                make_node().name(f"n{i}")
                .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                .label("slot", str(i)).obj()))
        plane.start()
        # Fill tier: 2 low-priority 4-cpu pods per node, pre-pinned so the
        # fill is deterministic and the cluster ends exactly full.
        fill_uids = set()
        for i in range(2 * N_NODES):
            p = (make_pod().name(f"fill-{i}").req({"cpu": "4"})
                 .priority(1).node_selector({"slot": str(i % N_NODES)})
                 .obj())
            fill_uids.add(p.uid)
            _call_http(base, "POST", "/api/v1/pods", pod_to_wire(p))
        assert _wait_true(
            lambda: _call_http(base, "GET",
                               "/api/v1/pods?summary=true")["bound"]
            >= 2 * N_NODES, timeout=90)
        # Observer counts DELETED fanouts per uid: exactly-once probe.
        observer = HTTPClientset(base)

        def on_delete(kind, old, new):
            if kind == "delete":
                deleted_counts[new.uid] = deleted_counts.get(new.uid, 0) + 1
        observer.on_pod_event(on_delete)
        # Storm: one pinned high-priority preemptor per node — each must
        # evict exactly one fill victim from ITS node, under whatever
        # bind conflicts the two shards produce against shared state.
        storm = [make_pod().name(f"hi-{i}").req({"cpu": "4"}).priority(100)
                 .node_selector({"slot": str(i)}).obj()
                 for i in range(N_NODES)]
        for p in storm:
            _call_http(base, "POST", "/api/v1/pods", pod_to_wire(p))
        assert _wait_true(
            lambda: all(api.store.pods[p.uid].node_name for p in storm
                        if p.uid in api.store.pods), timeout=120)
        assert not plane.errors(), plane.errors()
        # Oracle-identical: every preemptor on its pinned node.
        for i, p in enumerate(storm):
            assert api.store.pods[p.uid].node_name == f"n{i}"
        # Exactly-once victims: one victim per node, each DELETED fanout
        # observed exactly once, victims gone from the store.
        time.sleep(1.0)  # let the observer's stream drain
        victims = fill_uids - set(api.store.pods)
        assert len(victims) == N_NODES, len(victims)
        for uid in victims:
            assert deleted_counts.get(uid, 0) == 1, (uid, deleted_counts)
        # No overcommit anywhere (Omega validation held under conflicts).
        for name, u in api._usage.items():
            assert u["cpu"] <= 8000, (name, u)
    finally:
        if observer is not None:
            observer.close()
        plane.close()
        api.shutdown()


@pytest.mark.chaos
def test_leader_kill9_mid_flood_promotes_inside_ttl(tmp_path, monkeypatch):
    """Scenario 3: ``kill -9`` the LEADER while an adversarial flood is
    being shed. The exempt lane (lease CAS, replication control) is never
    queued behind tenant traffic, so promotion still completes within
    2.5x the lease TTL; the well-behaved tenant's pods bind exactly once
    oracle-identically; the flood keeps getting shed on the NEW leader."""
    from kubernetes_tpu.core.apiserver import (HTTPClientset, node_to_wire,
                                               pod_to_wire)
    from kubernetes_tpu.shard import ShardMember
    from kubernetes_tpu.shard.harness import scrape_labeled
    from kubernetes_tpu.testing.faults import ReplicaSet

    # Tight workload lane in every spawned apiserver (env seam) so a
    # 16-thread flood sheds deterministically; exempt has no override.
    monkeypatch.setenv("TPU_SCHED_APF_WORKLOAD", "2,4,2,1,0.25")
    N_PODS, N_NODES, LEASE = 160, 20, 2.0
    flood_ns = _pick_flood_namespace(["default"], queues=4, hand_size=1)
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=LEASE)
    members, drivers, clients = [], [], []
    flood_stop = threading.Event()
    flood_stats = []
    try:
        for i in range(2):
            fb = [u for u in rs.follower_urls if u != rs.follower_urls[i]] \
                + [rs.leader_url]
            http_cs = HTTPClientset(rs.follower_urls[i], fallbacks=fb)
            clients.append(http_cs)
            rcs = RetryingClientset(http_cs, retry=RetryConfig(
                initial_backoff=0.05, max_backoff=0.5, max_attempts=60,
                seed=17 + i, retry_after_cap=1.0))
            sched = Scheduler(clientset=rcs, deterministic_ties=True)
            member = ShardMember(sched, i, 2, lease_duration=30.0,
                                 identity=f"flood-shard-{i}")
            member.start_renewer()
            members.append(member)
            drivers.append(_Driver(sched))
        wcs = HTTPClientset(rs.follower_urls[0],
                            fallbacks=[rs.follower_urls[1], rs.leader_url])
        clients.append(wcs)
        writer = RetryingClientset(wcs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=60,
            seed=99, retry_after_cap=1.0))
        fcs = HTTPClientset(rs.follower_urls[1],
                            fallbacks=[rs.follower_urls[0], rs.leader_url])
        clients.append(fcs)

        def flood_worker(widx):
            from urllib.error import HTTPError
            stats = {"shed": 0, "posted": 0}
            flood_stats.append(stats)
            proto = (make_pod().name("proto").namespace(flood_ns)
                     .req({"cpu": "4096", "memory": "1Gi"}).obj())
            seq = 0
            while not flood_stop.is_set():
                seq += 1
                w = pod_to_wire(proto.clone_from_template(
                    f"fl-{widx}-{seq}"))
                try:
                    fcs._write_call("POST", "/api/v1/pods", w)
                    stats["posted"] += 1
                except HTTPError as e:
                    if e.code == 429:
                        stats["shed"] += 1
                except Exception:  # noqa: BLE001 - promotion in flight
                    time.sleep(0.05)

        nodes = [make_node().name(f"n{i}")
                 .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                 .label("slot", str(i)).obj() for i in range(N_NODES)]
        for n in nodes:
            writer.create_node(n)
        for cs in clients[:2]:
            assert _wait_true(lambda cs=cs: len(cs.nodes) == N_NODES)
        threads = [threading.Thread(target=flood_worker, args=(w,),
                                    daemon=True) for w in range(16)]
        for t in threads:
            t.start()
        pods = [make_pod().name(f"p{i}")
                .req({"cpu": "100m", "memory": "64Mi"})
                .node_selector({"slot": str(i % N_NODES)}).obj()
                for i in range(N_PODS)]
        t_promoted = None
        for i, p in enumerate(pods):
            writer.create_pod(p)
            if i == N_PODS // 2:
                rs.kill9_leader()  # SIGKILL mid-flood
                t_kill = time.monotonic()
                new_leader = rs.wait_for_leader(timeout=LEASE * 5)
                t_promoted = time.monotonic() - t_kill
                assert new_leader == rs.follower_urls[0], new_leader
                # The failover budget holds DESPITE the flood: the exempt
                # lane never queues behind tenant traffic.
                assert t_promoted < LEASE * 2.5, t_promoted
        assert _wait_true(
            lambda: _call_http(rs.follower_urls[1], "GET",
                               "/api/v1/pods?summary=true")["bound"]
            >= N_PODS, timeout=180)
        flood_stop.set()
        for t in threads:
            t.join(timeout=30)
        for d in drivers:
            assert not d.errors, f"scheduler crashed: {d.errors!r}"
        # Exactly-once, oracle-identical well-behaved binds.
        got = _call_http(rs.follower_urls[0], "GET", "/api/v1/pods")
        tenant = [p for p in got if p["namespace"] == "default"]
        bound = {p["name"]: p["nodeName"] for p in tenant if p["nodeName"]}
        assert len(bound) == N_PODS, f"only {len(bound)}/{N_PODS} bound"
        oracle = {f"p{i}": f"n{i % N_NODES}" for i in range(N_PODS)}
        diffs = {k: (oracle[k], bound.get(k)) for k in oracle
                 if oracle[k] != bound.get(k)}
        assert not diffs, f"{len(diffs)} divergences"
        # The flood really was shed — including on the NEW leader — and
        # the exempt lane was never queued or shed anywhere.
        assert sum(s["shed"] for s in flood_stats) > 0, flood_stats
        new_leader_url = rs.follower_urls[0]
        rejected = scrape_labeled(new_leader_url,
                                  "apiserver_flowcontrol_rejected_total",
                                  "priority_level")
        dispatched = scrape_labeled(new_leader_url,
                                    "apiserver_flowcontrol_dispatched_total",
                                    "priority_level")
        queued = scrape_labeled(new_leader_url,
                                "apiserver_flowcontrol_queued_total",
                                "priority_level")
        assert rejected.get("workload", 0) > 0
        assert rejected.get("exempt", 0) == 0
        assert queued.get("exempt", 0) == 0  # never queued, by construction
        assert dispatched.get("exempt", 0) > 0  # lease CAS kept landing
        # Promotion is fenced on the winner's epoch, as ever.
        st = rs.status(new_leader_url)
        assert st["role"] == "leader" and st["replEpoch"] >= 2
    finally:
        flood_stop.set()
        for m in members:
            m.stop()
        for d in drivers:
            d.stop()
        for cs in clients:
            cs.close()
        rs.stop()


# ---------------------------------------------------------------------------
# lock-order watchdog (testing/lockwatch.py; docs/ANALYSIS.md runtime half)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_lockwatch_reports_synthetic_abba_cycle():
    """Two threads taking the same pair of locks in opposite orders is a
    deadlock waiting for the right interleaving. The watch must report the
    cycle — WITH both acquisition sites — even though this run, executed
    serially, never deadlocks."""
    from kubernetes_tpu.testing.lockwatch import LockWatch

    watch = LockWatch()
    a = watch.wrap(threading.Lock(), "A")
    b = watch.wrap(threading.Lock(), "B")

    def ab():
        with a:
            with b:  # A -> B
                pass

    def ba():
        with b:
            with a:  # B -> A: closes the cycle
                pass

    for fn in (ab, ba):  # run serially: the ORDER GRAPH closes, not a deadlock
        t = threading.Thread(target=fn)
        t.start()
        t.join(timeout=5)
    cycles = watch.cycles()
    assert len(cycles) == 1
    cyc = cycles[0]
    assert set(cyc.locks) == {"A", "B"}
    # both witness edges name this file's acquisition sites
    assert len(cyc.sites) == 2
    for _a, _b, held_site, acq_site in cyc.sites:
        assert "test_faults.py" in held_site
        assert "test_faults.py" in acq_site
    with pytest.raises(AssertionError, match="lock-order cycle"):
        watch.assert_no_cycles()


@pytest.mark.chaos
def test_lockwatch_long_hold_and_rlock_reentry():
    """A hold across a blocking call is reported with its acquire site;
    RLock re-entry must NOT count as a second hold (no self-edges)."""
    from kubernetes_tpu.testing.lockwatch import LockWatch

    watch = LockWatch(hold_threshold=0.03)
    slow = watch.wrap(threading.Lock(), "slow")
    with slow:
        time.sleep(0.06)  # a blocking call under the lock
    assert [h.lock for h in watch.long_holds] == ["slow"]
    assert watch.long_holds[0].seconds >= 0.03
    assert "test_faults.py" in watch.long_holds[0].acquire_site

    r = watch.wrap(threading.RLock(), "re")
    with r:
        with r:  # re-entry: not a new hold, no "re"->"re" edge
            pass
    assert not watch.cycles()
    assert ("re", "re") not in watch.edges


@pytest.mark.chaos
def test_apiserver_chaos_run_under_lockwatch_is_cycle_free():
    """Instrument the REAL apiserver's write/broadcast locks and drive the
    full verb surface (creates, binds incl. a 409 conflict, status patch,
    lease CAS, watch attach) — the recorded acquisition-order graph must
    show the expected write-lock→broadcast-lock nesting and no cycles."""
    from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset
    from kubernetes_tpu.testing.lockwatch import LockWatch

    watch = LockWatch(hold_threshold=5.0)  # cycles only; holds not at issue
    api = APIServer()
    watch.instrument(api, "_lock", "_write_lock", prefix="apiserver")
    port = api.serve(0)
    client = None
    try:
        client = HTTPClientset(f"http://127.0.0.1:{port}")
        for n in _nodes(4, cpu=2):
            client.create_node(n)
        pods = _pods(8)
        for p in pods:
            client.create_pod(p)
        client.bind(pods[0], "n0")
        client.bind(pods[1], "n1")
        from urllib.error import HTTPError
        with pytest.raises(HTTPError):  # AlreadyBound 409: conflict branch
            client.bind(pods[0], "n3")
        client.patch_pod_status(pods[2], nominated_node_name="n2")
        assert client.upsert_lease("shard-0", "holder-a", 1.0) is not None
        assert client.upsert_lease("shard-0", "holder-b", 1.0) is None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(client.pods) < 8:
            time.sleep(0.05)
    finally:
        if client is not None:
            client.close()
        api.shutdown()
    assert watch.acquisitions > 10
    # the designed nesting was actually observed...
    assert ("apiserver._write_lock", "apiserver._lock") in watch.edges
    # ...and only that order, ever: no cycle anywhere in the graph
    watch.assert_no_cycles()


# ---------------------------------------------------------------------------
# satellite regressions (ADVICE r5 low items)
# ---------------------------------------------------------------------------


def test_collective_report_nested_replica_groups():
    """Non-greedy regex regression: only the FIRST of nested replica groups
    used to be classified — a later host-spanning group was misreported as
    ICI."""
    from kubernetes_tpu.parallel.mesh import collective_report
    hlo = ("%ar = f32[8]{0} all-reduce(%x), replica_groups={{0,1},{3,4}}, "
           "to_apply=%add\n"
           "%ag = f32[8]{0} all-gather(%y), replica_groups={0,1,2,3}, "
           "dimensions={0}\n")
    rep = collective_report(hlo, n_hosts=2, per_host=4)
    # {0,1} is host-local but {3,4} spans hosts 0 and 1 → DCN.
    assert rep["dcn"].get("all-reduce", 0) == 1
    # flat {0,1,2,3} stays within host 0 → ICI.
    assert rep["ici"].get("all-gather", 0) == 1


def test_resource_metrics_pending_pod_empty_node_label():
    """`/metrics/resources` renders pending pods with node="" (reference
    convention), never the literal string "None"."""
    from kubernetes_tpu.core.server import SchedulerServer
    cs = FakeClientset()
    sched = Scheduler(clientset=cs, deterministic_ties=True)
    pod = _pods(1)[0]
    pod.node_name = None  # the shape that used to render node="None"
    cs.create_pod(pod)
    server = SchedulerServer(sched)
    out = server.expose_resource_metrics()
    assert 'node=""' in out
    assert 'node="None"' not in out
    assert 'phase="Pending"' in out
