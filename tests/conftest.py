"""Test configuration: force JAX onto a virtual 8-device CPU mesh so sharding
tests run without TPU hardware (driver validates the real multi-chip path via
__graft_entry__.dryrun_multichip)."""

import os
import sys

# Force CPU even when the session env points at real TPU hardware. NOTE: the
# axon PJRT plugin ignores the JAX_PLATFORMS env var, so the config API must
# be used (before any backend initialization).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the equivalence/fuzz suites compile many
# distinct kernel static-combos (each ~0.5–5 s of backend_compile on a small
# CPU box), and every pytest process — plus every SUBPROCESS the chaos and
# shard-plane tests spawn — used to pay them all again. The cache is keyed
# on HLO+flags+compiler version, so hits are exact; a cold cache only costs
# the first run. Spawned schedulers inherit the env var (jax reads it at
# import when set) via testing/faults.spawn_ready's environment.
_JAX_CACHE = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "kubernetes-tpu-xla"))
jax.config.update("jax_compilation_cache_dir", _JAX_CACHE)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # Deterministic-seed fault-injection tests (tests/test_faults.py) run in
    # tier-1 under `chaos`; long kill/restart stress rides `slow` and is
    # excluded by the tier-1 `-m 'not slow'` selection.
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests (tier-1)")
    config.addinivalue_line(
        "markers", "slow: long-running stress tests (excluded from tier-1)")
