"""Binary wire codec (core/wire.py, docs/WIRE.md).

Covers: randomized round-trip fuzz vs the JSON oracle over protocol-shaped
objects (pods incl. slim projections, nodes, leases, seq+epoch WAL/ship
frames, continuation trailers); truncation fuzz at EVERY byte offset
asserting torn binary frames truncate exactly like torn JSON (WAL replay +
stream reads); Accept:-style negotiation end-to-end with per-surface
byte attribution; mixed-plane interop (binary client vs JSON-only server
and vice versa, a binary follower tailing a JSON leader across promotion,
old JSON WAL dirs recovered by the binary-default store); and the bulk
binding envelope's verdict mapping on the binary plane.
"""

import io
import json
import random
import time

import pytest

from kubernetes_tpu.core import wire
from kubernetes_tpu.core.apiserver import (
    APIServer,
    HTTPClientset,
    fetch_paged,
    node_to_wire,
    pod_to_wire,
)
from kubernetes_tpu.core.wal import DurableStore
from kubernetes_tpu.core.watchcache import slim_object
from kubernetes_tpu.replication import ReplicationTail
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# round-trip fuzz vs the JSON oracle
# ---------------------------------------------------------------------------


def _rand_scalar(rng: random.Random):
    kind = rng.randrange(8)
    if kind == 0:
        return None
    if kind == 1:
        return rng.random() < 0.5
    if kind == 2:
        # small ints (the inline fast path), boundary values, negatives,
        # and >64-bit magnitudes (Python ints are unbounded)
        return rng.choice([0, 1, 0xBE, 0xBF, 0xC0, 255, -1, -7,
                           2**31, -2**31, 2**63 + 12345, -2**70,
                           rng.randrange(-10**6, 10**6)])
    if kind == 3:
        return rng.choice([0.0, -0.5, 3.141592653589793, 1e-12, 1e300,
                           rng.random() * 1e6])
    if kind == 4:
        return ""
    if kind == 5:
        # repeated protocol-ish strings (the intern table's bread)
        return rng.choice(["nodeName", "uid", "ADDED", "default",
                           "zone-7", "node-00123"])
    if kind == 6:
        return "uid-%032x" % rng.getrandbits(128)
    return rng.choice(["ünïcode-∞", "tab\tnl\nquote\"", "汉字", "🦀",
                       "x" * rng.randrange(0, 300)])


def _rand_obj(rng: random.Random, depth: int = 0):
    if depth >= 3 or rng.random() < 0.4:
        return _rand_scalar(rng)
    if rng.random() < 0.5:
        return {("k%d" % i if rng.random() < 0.5
                 else str(_rand_scalar(rng))): _rand_obj(rng, depth + 1)
                for i in range(rng.randrange(0, 6))}
    return [_rand_obj(rng, depth + 1) for _ in range(rng.randrange(0, 6))]


class TestRoundTripFuzz:
    def test_randomized_objects_vs_json_oracle(self):
        rng = random.Random(0xC0DEC)
        for i in range(400):
            obj = _rand_obj(rng)
            frame = wire.encode_binary(obj)
            got = wire.decode_binary(frame)
            oracle = json.loads(json.dumps(obj))
            assert got == oracle == obj, (i, obj)
            # the sniffing decoder agrees on both planes
            assert wire.decode(frame) == obj
            assert wire.decode(wire.encode(obj, wire.JSON)) == obj

    def test_protocol_shapes_roundtrip(self):
        rng = random.Random(7)
        for i in range(60):
            pod = (make_pod().name(f"p{i}")
                   .req({"cpu": f"{rng.randrange(1, 2000)}m",
                         "memory": f"{rng.randrange(1, 512)}Mi"})
                   .labels({"app": f"a{i % 5}", "tier": "fuzz"})
                   .priority(rng.randrange(0, 100)).obj())
            node = (make_node().name(f"n{i}")
                    .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                    .zone(f"z{i % 3}").obj())
            pw, nw = pod_to_wire(pod), node_to_wire(node)
            shapes = [
                {"type": "ADDED", "object": pw, "rv": i + 1},
                {"type": "MODIFIED", "object": slim_object(pw), "rv": i + 2},
                {"type": "BOUND",
                 "object": {"uid": pw["uid"], "nodeName": nw["name"]},
                 "rv": i + 3},
                {"type": "ADDED", "object": nw, "rv": i + 4},
                # seq+epoch-stamped WAL/ship frame
                {"kind": "pods", "type": "ADDED", "object": pw,
                 "rv": i + 1, "seq": 10_000 + i, "epoch": 3},
                {"kind": "leases", "type": "LEASE",
                 "object": {"name": "shard-0", "holder": f"s{i}",
                            "duration": 2.5, "transitions": i}},
                # PAGE trailer (continuation tokens ride it opaque)
                {"type": "PAGE", "rv": i, "listRv": i - 1, "epoch": "e1",
                 "continue": "dG9rZW4="},
            ]
            for obj in shapes:
                frame = wire.encode_binary(obj)
                assert wire.decode_binary(frame) == obj
                assert json.loads(json.dumps(obj)) == obj

    def test_bytes_passthrough_binary_only(self):
        payload = {"raw": b"\x00\xbf\x01already-encoded\xff"}
        assert wire.decode_binary(wire.encode_binary(payload)) == payload
        with pytest.raises(TypeError):
            wire.encode(payload, wire.JSON)

    def test_intern_table_resets_per_frame(self):
        # the same novel strings in two frames: each frame is
        # self-contained, so the SECOND decodes alone (stream prefixes can
        # be truncated away without poisoning later frames)
        obj = {"novel-key-xyz": ["novel-key-xyz", "novel-value-abc",
                                 "novel-value-abc"]}
        f1, f2 = wire.encode_binary(obj), wire.encode_binary(obj)
        assert f1 == f2
        assert wire.decode_binary(f2) == obj
        # refs are cheaper than defs: the repeated strings shrank frame 1
        assert len(f1) < len((json.dumps(obj) + "\n").encode())

    def test_well_known_table_is_duplicate_free_and_versioned(self):
        # a duplicate entry would shadow an index and corrupt every frame;
        # the version byte is what lets a reader key its seed table
        assert len(set(wire.WELL_KNOWN)) == len(wire.WELL_KNOWN)
        assert wire.VERSION == 1
        assert wire.encode_binary({})[1] == wire.VERSION


# ---------------------------------------------------------------------------
# truncation fuzz: torn binary == torn JSON, at every byte offset
# ---------------------------------------------------------------------------


def _fuzz_records():
    return [
        {"kind": "pods", "type": "ADDED", "rv": i,
         "object": {"uid": f"u{i}", "name": f"p{i}", "deletionTs": None,
                    "requests": {"cpu": 100 + i, "memory": 2.5 * i,
                                 "scalar": {}},
                    "labels": {"app": "fuzz", "note": "ünïcode-∞"}},
         "seq": i, "epoch": 1}
        for i in range(1, 7)
    ]


class TestTruncationFuzz:
    @pytest.mark.parametrize("codec", [wire.BINARY, wire.JSON])
    def test_wal_truncated_at_every_offset(self, tmp_path, codec):
        """Identical torn-tail contract on both codecs: at EVERY byte
        offset, replay yields exactly the longest clean prefix of records,
        counts at most one torn record, and truncates the file back to the
        last good frame so the next append starts clean."""
        recs = _fuzz_records()
        src = tmp_path / "src"
        ds = DurableStore(str(src), codec=codec)
        ds.load()
        for r in recs:
            ds.append(r)
        ds.close()
        buf = (src / DurableStore.WAL).read_bytes()
        # record boundaries via the same sniffing scanner replay uses
        bounds, pos = [0], 0
        while True:
            got = wire.scan(buf, pos)
            if got is None:
                break
            _, pos = got
            bounds.append(pos)
        assert len(bounds) == len(recs) + 1 and bounds[-1] == len(buf)
        for cut in range(len(buf) + 1):
            d = tmp_path / f"cut-{codec}-{cut}"
            d.mkdir()
            (d / DurableStore.WAL).write_bytes(buf[:cut])
            ds2 = DurableStore(str(d), codec=codec)
            _snap, replayed = ds2.load()
            n_good = max(i for i, b in enumerate(bounds) if b <= cut)
            assert replayed == recs[:n_good], (codec, cut)
            at_boundary = cut in bounds
            assert ds2.torn_records_discarded == (0 if at_boundary else 1), (
                codec, cut)
            # the torn tail is gone from disk: a new append starts clean
            ds2.append(recs[0])
            ds2.close()
            ds3 = DurableStore(str(d), codec=codec)
            _snap, replayed = ds3.load()
            assert replayed == recs[:n_good] + [recs[0]], (codec, cut)
            assert ds3.torn_records_discarded == 0
            ds3.close()

    @pytest.mark.parametrize("codec", [wire.BINARY, wire.JSON])
    def test_stream_torn_at_every_offset_never_yields_garbage(self, codec):
        """The follower-tail / watch-stream read path: a stream cut at any
        byte yields exactly a clean prefix of records, then EOF or a torn
        error — never a corrupt record (the json.JSONDecodeError analogue
        is WireError)."""
        recs = _fuzz_records()
        buf = b"".join(wire.encode(r, codec) for r in recs)
        for cut in range(len(buf) + 1):
            fp = io.BytesIO(buf[:cut])
            got = []
            try:
                while True:
                    item = wire.read_event(fp)
                    if item is None:
                        break
                    got.append(item[0])
            except (wire.WireError, ValueError):
                pass
            assert got == recs[:len(got)], (codec, cut)

    def test_mixed_codec_wal_history_replays(self, tmp_path):
        """An old JSON WAL a binary-default server appended to: one file,
        two codecs, replayed record-by-record by header sniffing."""
        d = str(tmp_path / "mixed")
        recs = _fuzz_records()
        ds = DurableStore(d, codec=wire.JSON)
        ds.load()
        for r in recs[:3]:
            ds.append(r)
        ds.close()
        ds2 = DurableStore(d)  # binary default (CRC frames since PR 17)
        assert ds2.codec == wire.BINARY_CRC
        _snap, replayed = ds2.load()
        assert replayed == recs[:3]
        for r in recs[3:]:
            ds2.append(r)
        ds2.close()
        ds3 = DurableStore(d)
        _snap, replayed = ds3.load()
        assert replayed == recs and ds3.torn_records_discarded == 0
        ds3.close()


# ---------------------------------------------------------------------------
# negotiation + per-surface attribution, end-to-end over HTTP
# ---------------------------------------------------------------------------


def _pod(name, cpu="100m"):
    return make_pod().name(name).req({"cpu": cpu, "memory": "64Mi"}).obj()


def _node(name, cpu=8):
    return (make_node().name(name)
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110}).obj())


class TestNegotiation:
    def test_binary_negotiated_end_to_end_with_surface_attribution(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(30):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 30, msg="reflector sync")
            # decode plane: everything arrived binary, nothing full-JSON
            assert cs.wire_decode_events[("full", wire.BINARY)] >= 31
            assert cs.wire_decode_events[("full", wire.JSON)] == 0
            assert cs.wire_decode_bytes[("full", wire.BINARY)] > 0
            # live watch events ride binary too
            api.store.create_pod(_pod("p-live"))
            _wait(lambda: "p-live" in {p.name for p in cs.pods.values()},
                  msg="live event")
            # bulk bindings: the negotiation learned from earlier replies,
            # so the envelope goes out binary and verdicts come back binary
            cs._call("GET", "/api/v1/pods?summary=true")  # prime _ka
            errs = cs.bind_many([(cs.pods[u], "n0")
                                 for u in list(cs.pods)[:5]])
            assert errs == [None] * 5
            # server-side attribution: binary bytes on list/watch/bindings
            surfaces = {s for (c, s), v in api.wire_bytes.items()
                        if c == wire.BINARY and v > 0}
            assert {"list", "watch", "bindings"} <= surfaces, (
                api.wire_bytes)
            # binary is strictly smaller than the JSON plane would be:
            # re-encode one pod event both ways
            ev = {"type": "ADDED", "object": pod_to_wire(_pod("x")), "rv": 1}
            assert len(wire.encode(ev, wire.BINARY)) * 2 < len(
                wire.encode(ev, wire.JSON))
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_binary_client_vs_json_only_server_falls_back(self):
        api = APIServer()
        api.json_only = True   # a pre-wire server: ignores every offer
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(8):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 8, msg="reflector sync")
            assert cs.wire_decode_events[("full", wire.JSON)] >= 9
            assert cs.wire_decode_events[("full", wire.BINARY)] == 0
            # writes work and stay JSON (the client never learned binary)
            cs.bind(cs.pods[list(cs.pods)[0]], "n0")
            _wait(lambda: len(cs.bindings) == 1, msg="bound event")
            assert all(v == 0 for (c, _s), v in api.wire_bytes.items()
                       if c == wire.BINARY), api.wire_bytes
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_json_client_vs_binary_server_falls_back(self, monkeypatch):
        # a JSON-pinned CLIENT (no Accept offer) against a binary-willing
        # server: every surface answers JSON
        monkeypatch.setattr(wire, "client_headers", lambda: {})
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(8):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 8, msg="reflector sync")
            assert cs.wire_decode_events[("full", wire.JSON)] >= 9
            assert cs.wire_decode_events[("full", wire.BINARY)] == 0
            assert all(v == 0 for (c, _s), v in api.wire_bytes.items()
                       if c == wire.BINARY), api.wire_bytes
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_paged_list_oracle_identical_across_codecs(self, monkeypatch):
        api = APIServer()
        port = api.serve(0)
        try:
            for i in range(37):
                api.store.create_pod(_pod(f"p{i:03d}"))
            base = f"http://127.0.0.1:{port}"
            binary = fetch_paged(base, "pods", limit=7)
            monkeypatch.setattr(wire, "client_headers", lambda: {})
            as_json = fetch_paged(base, "pods", limit=7)
            assert binary == as_json and len(binary) == 37
        finally:
            api.shutdown()

    def test_bulk_binding_verdicts_on_the_binary_plane(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0", cpu=1))
            api.store.create_pod(_pod("p0", cpu="600m"))
            api.store.create_pod(_pod("p1", cpu="600m"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 2, msg="sync")
            cs._call("GET", "/api/v1/pods?summary=true")  # learn binary
            uids = sorted(cs.pods)
            errs = cs.bind_many([(cs.pods[uids[0]], "n0"),
                                 (cs.pods[uids[1]], "n0")])
            # one commits, one loses Omega validation with a 409 verdict
            # whose reason survives the binary envelope
            assert errs[0] is None
            assert errs[1] is not None and errs[1].code == 409
            assert "OutOfCapacity" in errs[1].read().decode()
            assert api.wire_bytes[("binary", "bindings")] > 0
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()


# ---------------------------------------------------------------------------
# mixed-plane replication interop
# ---------------------------------------------------------------------------


class TestReplicationInterop:
    def test_binary_follower_tails_json_leader_across_promotion(self):
        """A binary-default follower bootstraps from and tails a JSON-only
        leader (sniff-decoded frame by frame), converges, and promotes
        cleanly when the leader dies — codec continuity is not part of the
        stream contract."""
        leader = APIServer()
        leader.json_only = True
        lport = leader.serve(0)
        follower = APIServer()
        tail = ReplicationTail(follower, f"http://127.0.0.1:{lport}",
                               rank=1, lease_duration=0.5)
        fport = follower.serve(0)
        follower.repl_peers.update(
            {0: f"http://127.0.0.1:{lport}", 1: f"http://127.0.0.1:{fport}"})
        try:
            leader.store.create_node(_node("n0"))
            for i in range(10):
                leader.store.create_pod(_pod(f"p{i}"))
            tail.bootstrap()
            tail.start()
            _wait(lambda: follower._repl_seq >= leader._repl_seq
                  and len(follower.store.pods) == 10, msg="convergence")
            # mid-stream traffic keeps flowing json -> binary store
            for i in range(10, 16):
                leader.store.create_pod(_pod(f"p{i}"))
            _wait(lambda: len(follower.store.pods) == 16, msg="tail")
            old_epoch = follower.repl_epoch
            leader.shutdown()
            _wait(lambda: follower.role == "leader", timeout=20.0,
                  msg="promotion")
            assert follower.repl_epoch > old_epoch
            # the promoted (binary-plane) leader accepts writes
            follower.store.create_pod(_pod("p-after"))
            assert len(follower.store.pods) == 17
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()

    def test_old_json_wal_dir_recovered_by_binary_default_server(
            self, tmp_path, monkeypatch):
        """A data dir written entirely on the JSON plane (a pre-wire
        server) recovers under the binary-default store; new appends go
        binary into the same file; a third boot replays the mixed
        history."""
        d = str(tmp_path / "state")
        monkeypatch.setenv("TPU_SCHED_WIRE", "json")
        api = APIServer(data_dir=d)
        assert api.persistence.codec == wire.JSON
        api.store.create_node(_node("n0"))
        for i in range(6):
            api.store.create_pod(_pod(f"p{i}"))
        epoch = api.epoch
        api.shutdown()
        monkeypatch.delenv("TPU_SCHED_WIRE")
        api2 = APIServer(data_dir=d)
        assert api2.persistence.codec == wire.BINARY_CRC
        assert api2.epoch == epoch
        assert len(api2.store.pods) == 6
        assert api2.persistence.torn_records_discarded == 0
        api2.store.create_pod(_pod("p-binary"))
        api2.shutdown()
        api3 = APIServer(data_dir=d)
        assert len(api3.store.pods) == 7
        assert api3.persistence.torn_records_discarded == 0
        api3.shutdown()
