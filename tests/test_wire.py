"""Binary wire codec (core/wire.py, docs/WIRE.md).

Covers: randomized round-trip fuzz vs the JSON oracle over protocol-shaped
objects (pods incl. slim projections, nodes, leases, seq+epoch WAL/ship
frames, continuation trailers); truncation fuzz at EVERY byte offset
asserting torn binary frames truncate exactly like torn JSON (WAL replay +
stream reads); Accept:-style negotiation end-to-end with per-surface
byte attribution; mixed-plane interop (binary client vs JSON-only server
and vice versa, a binary follower tailing a JSON leader across promotion,
old JSON WAL dirs recovered by the binary-default store); and the bulk
binding envelope's verdict mapping on the binary plane.
"""

import io
import json
import random
import time

import pytest

from kubernetes_tpu.core import wire
from kubernetes_tpu.core.apiserver import (
    APIServer,
    HTTPClientset,
    fetch_paged,
    node_to_wire,
    pod_to_wire,
)
from kubernetes_tpu.core.wal import DurableStore
from kubernetes_tpu.core.watchcache import slim_object
from kubernetes_tpu.replication import ReplicationTail
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# round-trip fuzz vs the JSON oracle
# ---------------------------------------------------------------------------


def _rand_scalar(rng: random.Random):
    kind = rng.randrange(8)
    if kind == 0:
        return None
    if kind == 1:
        return rng.random() < 0.5
    if kind == 2:
        # small ints (the inline fast path), boundary values, negatives,
        # and >64-bit magnitudes (Python ints are unbounded)
        return rng.choice([0, 1, 0xBE, 0xBF, 0xC0, 255, -1, -7,
                           2**31, -2**31, 2**63 + 12345, -2**70,
                           rng.randrange(-10**6, 10**6)])
    if kind == 3:
        return rng.choice([0.0, -0.5, 3.141592653589793, 1e-12, 1e300,
                           rng.random() * 1e6])
    if kind == 4:
        return ""
    if kind == 5:
        # repeated protocol-ish strings (the intern table's bread)
        return rng.choice(["nodeName", "uid", "ADDED", "default",
                           "zone-7", "node-00123"])
    if kind == 6:
        return "uid-%032x" % rng.getrandbits(128)
    return rng.choice(["ünïcode-∞", "tab\tnl\nquote\"", "汉字", "🦀",
                       "x" * rng.randrange(0, 300)])


def _rand_obj(rng: random.Random, depth: int = 0):
    if depth >= 3 or rng.random() < 0.4:
        return _rand_scalar(rng)
    if rng.random() < 0.5:
        return {("k%d" % i if rng.random() < 0.5
                 else str(_rand_scalar(rng))): _rand_obj(rng, depth + 1)
                for i in range(rng.randrange(0, 6))}
    return [_rand_obj(rng, depth + 1) for _ in range(rng.randrange(0, 6))]


class TestRoundTripFuzz:
    def test_randomized_objects_vs_json_oracle(self):
        rng = random.Random(0xC0DEC)
        for i in range(400):
            obj = _rand_obj(rng)
            frame = wire.encode_binary(obj)
            got = wire.decode_binary(frame)
            oracle = json.loads(json.dumps(obj))
            assert got == oracle == obj, (i, obj)
            # the sniffing decoder agrees on both planes
            assert wire.decode(frame) == obj
            assert wire.decode(wire.encode(obj, wire.JSON)) == obj

    def test_protocol_shapes_roundtrip(self):
        rng = random.Random(7)
        for i in range(60):
            pod = (make_pod().name(f"p{i}")
                   .req({"cpu": f"{rng.randrange(1, 2000)}m",
                         "memory": f"{rng.randrange(1, 512)}Mi"})
                   .labels({"app": f"a{i % 5}", "tier": "fuzz"})
                   .priority(rng.randrange(0, 100)).obj())
            node = (make_node().name(f"n{i}")
                    .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                    .zone(f"z{i % 3}").obj())
            pw, nw = pod_to_wire(pod), node_to_wire(node)
            shapes = [
                {"type": "ADDED", "object": pw, "rv": i + 1},
                {"type": "MODIFIED", "object": slim_object(pw), "rv": i + 2},
                {"type": "BOUND",
                 "object": {"uid": pw["uid"], "nodeName": nw["name"]},
                 "rv": i + 3},
                {"type": "ADDED", "object": nw, "rv": i + 4},
                # seq+epoch-stamped WAL/ship frame
                {"kind": "pods", "type": "ADDED", "object": pw,
                 "rv": i + 1, "seq": 10_000 + i, "epoch": 3},
                {"kind": "leases", "type": "LEASE",
                 "object": {"name": "shard-0", "holder": f"s{i}",
                            "duration": 2.5, "transitions": i}},
                # PAGE trailer (continuation tokens ride it opaque)
                {"type": "PAGE", "rv": i, "listRv": i - 1, "epoch": "e1",
                 "continue": "dG9rZW4="},
            ]
            for obj in shapes:
                frame = wire.encode_binary(obj)
                assert wire.decode_binary(frame) == obj
                assert json.loads(json.dumps(obj)) == obj

    def test_bytes_passthrough_binary_only(self):
        payload = {"raw": b"\x00\xbf\x01already-encoded\xff"}
        assert wire.decode_binary(wire.encode_binary(payload)) == payload
        with pytest.raises(TypeError):
            wire.encode(payload, wire.JSON)

    def test_intern_table_resets_per_frame(self):
        # the same novel strings in two frames: each frame is
        # self-contained, so the SECOND decodes alone (stream prefixes can
        # be truncated away without poisoning later frames)
        obj = {"novel-key-xyz": ["novel-key-xyz", "novel-value-abc",
                                 "novel-value-abc"]}
        f1, f2 = wire.encode_binary(obj), wire.encode_binary(obj)
        assert f1 == f2
        assert wire.decode_binary(f2) == obj
        # refs are cheaper than defs: the repeated strings shrank frame 1
        assert len(f1) < len((json.dumps(obj) + "\n").encode())

    def test_well_known_table_is_duplicate_free_and_versioned(self):
        # a duplicate entry would shadow an index and corrupt every frame;
        # the version byte is what lets a reader key its seed table
        assert len(set(wire.WELL_KNOWN)) == len(wire.WELL_KNOWN)
        assert wire.VERSION == 1
        assert wire.encode_binary({})[1] == wire.VERSION


# ---------------------------------------------------------------------------
# truncation fuzz: torn binary == torn JSON, at every byte offset
# ---------------------------------------------------------------------------


def _fuzz_records():
    return [
        {"kind": "pods", "type": "ADDED", "rv": i,
         "object": {"uid": f"u{i}", "name": f"p{i}", "deletionTs": None,
                    "requests": {"cpu": 100 + i, "memory": 2.5 * i,
                                 "scalar": {}},
                    "labels": {"app": "fuzz", "note": "ünïcode-∞"}},
         "seq": i, "epoch": 1}
        for i in range(1, 7)
    ]


class TestTruncationFuzz:
    @pytest.mark.parametrize("codec", [wire.BINARY, wire.JSON])
    def test_wal_truncated_at_every_offset(self, tmp_path, codec):
        """Identical torn-tail contract on both codecs: at EVERY byte
        offset, replay yields exactly the longest clean prefix of records,
        counts at most one torn record, and truncates the file back to the
        last good frame so the next append starts clean."""
        recs = _fuzz_records()
        src = tmp_path / "src"
        ds = DurableStore(str(src), codec=codec)
        ds.load()
        for r in recs:
            ds.append(r)
        ds.close()
        buf = (src / DurableStore.WAL).read_bytes()
        # record boundaries via the same sniffing scanner replay uses
        bounds, pos = [0], 0
        while True:
            got = wire.scan(buf, pos)
            if got is None:
                break
            _, pos = got
            bounds.append(pos)
        assert len(bounds) == len(recs) + 1 and bounds[-1] == len(buf)
        for cut in range(len(buf) + 1):
            d = tmp_path / f"cut-{codec}-{cut}"
            d.mkdir()
            (d / DurableStore.WAL).write_bytes(buf[:cut])
            ds2 = DurableStore(str(d), codec=codec)
            _snap, replayed = ds2.load()
            n_good = max(i for i, b in enumerate(bounds) if b <= cut)
            assert replayed == recs[:n_good], (codec, cut)
            at_boundary = cut in bounds
            assert ds2.torn_records_discarded == (0 if at_boundary else 1), (
                codec, cut)
            # the torn tail is gone from disk: a new append starts clean
            ds2.append(recs[0])
            ds2.close()
            ds3 = DurableStore(str(d), codec=codec)
            _snap, replayed = ds3.load()
            assert replayed == recs[:n_good] + [recs[0]], (codec, cut)
            assert ds3.torn_records_discarded == 0
            ds3.close()

    @pytest.mark.parametrize("codec", [wire.BINARY, wire.JSON])
    def test_stream_torn_at_every_offset_never_yields_garbage(self, codec):
        """The follower-tail / watch-stream read path: a stream cut at any
        byte yields exactly a clean prefix of records, then EOF or a torn
        error — never a corrupt record (the json.JSONDecodeError analogue
        is WireError)."""
        recs = _fuzz_records()
        buf = b"".join(wire.encode(r, codec) for r in recs)
        for cut in range(len(buf) + 1):
            fp = io.BytesIO(buf[:cut])
            got = []
            try:
                while True:
                    item = wire.read_event(fp)
                    if item is None:
                        break
                    got.append(item[0])
            except (wire.WireError, ValueError):
                pass
            assert got == recs[:len(got)], (codec, cut)

    def test_mixed_codec_wal_history_replays(self, tmp_path):
        """An old JSON WAL a binary-default server appended to: one file,
        two codecs, replayed record-by-record by header sniffing."""
        d = str(tmp_path / "mixed")
        recs = _fuzz_records()
        ds = DurableStore(d, codec=wire.JSON)
        ds.load()
        for r in recs[:3]:
            ds.append(r)
        ds.close()
        ds2 = DurableStore(d)  # binary default (CRC frames since PR 17)
        assert ds2.codec == wire.BINARY_CRC
        _snap, replayed = ds2.load()
        assert replayed == recs[:3]
        for r in recs[3:]:
            ds2.append(r)
        ds2.close()
        ds3 = DurableStore(d)
        _snap, replayed = ds3.load()
        assert replayed == recs and ds3.torn_records_discarded == 0
        ds3.close()


# ---------------------------------------------------------------------------
# negotiation + per-surface attribution, end-to-end over HTTP
# ---------------------------------------------------------------------------


def _pod(name, cpu="100m"):
    return make_pod().name(name).req({"cpu": cpu, "memory": "64Mi"}).obj()


def _node(name, cpu=8):
    return (make_node().name(name)
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110}).obj())


class TestNegotiation:
    def test_binary_negotiated_end_to_end_with_surface_attribution(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(30):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 30, msg="reflector sync")
            # decode plane: everything arrived binary, nothing full-JSON
            assert cs.wire_decode_events[("full", wire.BINARY)] >= 31
            assert cs.wire_decode_events[("full", wire.JSON)] == 0
            assert cs.wire_decode_bytes[("full", wire.BINARY)] > 0
            # live watch events ride binary too
            api.store.create_pod(_pod("p-live"))
            _wait(lambda: "p-live" in {p.name for p in cs.pods.values()},
                  msg="live event")
            # bulk bindings: the negotiation learned from earlier replies,
            # so the envelope goes out binary and verdicts come back binary
            cs._call("GET", "/api/v1/pods?summary=true")  # prime _ka
            errs = cs.bind_many([(cs.pods[u], "n0")
                                 for u in list(cs.pods)[:5]])
            assert errs == [None] * 5
            # server-side attribution: binary bytes on list/watch/bindings
            surfaces = {s for (c, s), v in api.wire_bytes.items()
                        if c == wire.BINARY and v > 0}
            assert {"list", "watch", "bindings"} <= surfaces, (
                api.wire_bytes)
            # binary is strictly smaller than the JSON plane would be:
            # re-encode one pod event both ways
            ev = {"type": "ADDED", "object": pod_to_wire(_pod("x")), "rv": 1}
            assert len(wire.encode(ev, wire.BINARY)) * 2 < len(
                wire.encode(ev, wire.JSON))
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_binary_client_vs_json_only_server_falls_back(self):
        api = APIServer()
        api.json_only = True   # a pre-wire server: ignores every offer
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(8):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 8, msg="reflector sync")
            assert cs.wire_decode_events[("full", wire.JSON)] >= 9
            assert cs.wire_decode_events[("full", wire.BINARY)] == 0
            # writes work and stay JSON (the client never learned binary)
            cs.bind(cs.pods[list(cs.pods)[0]], "n0")
            _wait(lambda: len(cs.bindings) == 1, msg="bound event")
            assert all(v == 0 for (c, _s), v in api.wire_bytes.items()
                       if c == wire.BINARY), api.wire_bytes
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_json_client_vs_binary_server_falls_back(self, monkeypatch):
        # a JSON-pinned CLIENT (no Accept offer) against a binary-willing
        # server: every surface answers JSON
        monkeypatch.setattr(wire, "client_headers", lambda: {})
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            for i in range(8):
                api.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 8, msg="reflector sync")
            assert cs.wire_decode_events[("full", wire.JSON)] >= 9
            assert cs.wire_decode_events[("full", wire.BINARY)] == 0
            assert all(v == 0 for (c, _s), v in api.wire_bytes.items()
                       if c == wire.BINARY), api.wire_bytes
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_paged_list_oracle_identical_across_codecs(self, monkeypatch):
        api = APIServer()
        port = api.serve(0)
        try:
            for i in range(37):
                api.store.create_pod(_pod(f"p{i:03d}"))
            base = f"http://127.0.0.1:{port}"
            binary = fetch_paged(base, "pods", limit=7)
            monkeypatch.setattr(wire, "client_headers", lambda: {})
            as_json = fetch_paged(base, "pods", limit=7)
            assert binary == as_json and len(binary) == 37
        finally:
            api.shutdown()

    def test_bulk_binding_verdicts_on_the_binary_plane(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0", cpu=1))
            api.store.create_pod(_pod("p0", cpu="600m"))
            api.store.create_pod(_pod("p1", cpu="600m"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.pods) == 2, msg="sync")
            cs._call("GET", "/api/v1/pods?summary=true")  # learn binary
            uids = sorted(cs.pods)
            errs = cs.bind_many([(cs.pods[uids[0]], "n0"),
                                 (cs.pods[uids[1]], "n0")])
            # one commits, one loses Omega validation with a 409 verdict
            # whose reason survives the binary envelope
            assert errs[0] is None
            assert errs[1] is not None and errs[1].code == 409
            assert "OutOfCapacity" in errs[1].read().decode()
            assert api.wire_bytes[("binary", "bindings")] > 0
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()


# ---------------------------------------------------------------------------
# mixed-plane replication interop
# ---------------------------------------------------------------------------


class TestReplicationInterop:
    def test_binary_follower_tails_json_leader_across_promotion(self):
        """A binary-default follower bootstraps from and tails a JSON-only
        leader (sniff-decoded frame by frame), converges, and promotes
        cleanly when the leader dies — codec continuity is not part of the
        stream contract."""
        leader = APIServer()
        leader.json_only = True
        lport = leader.serve(0)
        follower = APIServer()
        tail = ReplicationTail(follower, f"http://127.0.0.1:{lport}",
                               rank=1, lease_duration=0.5)
        fport = follower.serve(0)
        follower.repl_peers.update(
            {0: f"http://127.0.0.1:{lport}", 1: f"http://127.0.0.1:{fport}"})
        try:
            leader.store.create_node(_node("n0"))
            for i in range(10):
                leader.store.create_pod(_pod(f"p{i}"))
            tail.bootstrap()
            tail.start()
            _wait(lambda: follower._repl_seq >= leader._repl_seq
                  and len(follower.store.pods) == 10, msg="convergence")
            # mid-stream traffic keeps flowing json -> binary store
            for i in range(10, 16):
                leader.store.create_pod(_pod(f"p{i}"))
            _wait(lambda: len(follower.store.pods) == 16, msg="tail")
            old_epoch = follower.repl_epoch
            leader.shutdown()
            _wait(lambda: follower.role == "leader", timeout=20.0,
                  msg="promotion")
            assert follower.repl_epoch > old_epoch
            # the promoted (binary-plane) leader accepts writes
            follower.store.create_pod(_pod("p-after"))
            assert len(follower.store.pods) == 17
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()

    def test_old_json_wal_dir_recovered_by_binary_default_server(
            self, tmp_path, monkeypatch):
        """A data dir written entirely on the JSON plane (a pre-wire
        server) recovers under the binary-default store; new appends go
        binary into the same file; a third boot replays the mixed
        history."""
        d = str(tmp_path / "state")
        monkeypatch.setenv("TPU_SCHED_WIRE", "json")
        api = APIServer(data_dir=d)
        assert api.persistence.codec == wire.JSON
        api.store.create_node(_node("n0"))
        for i in range(6):
            api.store.create_pod(_pod(f"p{i}"))
        epoch = api.epoch
        api.shutdown()
        monkeypatch.delenv("TPU_SCHED_WIRE")
        api2 = APIServer(data_dir=d)
        assert api2.persistence.codec == wire.BINARY_CRC
        assert api2.epoch == epoch
        assert len(api2.store.pods) == 6
        assert api2.persistence.torn_records_discarded == 0
        api2.store.create_pod(_pod("p-binary"))
        api2.shutdown()
        api3 = APIServer(data_dir=d)
        assert len(api3.store.pods) == 7
        assert api3.persistence.torn_records_discarded == 0
        api3.shutdown()


# ---------------------------------------------------------------------------
# delta plane (PR 18): diff/patch fuzz vs the JSON oracle
# ---------------------------------------------------------------------------


def _rand_dict(rng: random.Random):
    return {"k%d" % i: _rand_obj(rng, 1) for i in range(rng.randrange(1, 7))}


def _mutate(rng: random.Random, obj: dict) -> dict:
    """A handful of field-level edits — set / delete / replace, sometimes
    inside a nested dict — the churn shape DELTA records exist for."""
    new = json.loads(json.dumps(obj))   # deep copy via the oracle
    for _ in range(rng.randrange(1, 4)):
        target = new
        while isinstance(target, dict) and target and rng.random() < 0.5:
            v = target[rng.choice(sorted(target))]
            if isinstance(v, dict) and v:
                target = v
            else:
                break
        if not isinstance(target, dict):
            continue
        action = rng.randrange(3)
        if action == 0 or not target:
            target["m%d" % rng.randrange(5)] = _rand_scalar(rng)
        elif action == 1:
            del target[rng.choice(sorted(target))]
        else:
            target[rng.choice(sorted(target))] = _rand_obj(rng, 2)
    return new


class TestDeltaDiffPatch:
    def test_randomized_diff_apply_vs_json_oracle(self):
        rng = random.Random(0xDE17A)
        hits = 0
        for i in range(400):
            old = _rand_dict(rng)
            new = _mutate(rng, old)
            before = json.loads(json.dumps(old))
            patch = wire.diff_obj(old, new)
            if patch is None:
                continue     # too many ops: the full-frame path
            hits += 1
            got = wire.apply_patch(old, patch)
            oracle = json.loads(json.dumps(new))
            assert got == oracle == new, (i, old, new, patch)
            # copy-on-write: the base the diff was minted against is
            # untouched — every attached stream and the WAL share it
            assert old == before, i
            # the patch itself survives the binary frame bit-exactly
            assert wire.decode_binary(wire.encode_binary(patch)) == patch
        assert hits > 300

    def test_identical_objects_diff_to_empty_patch(self):
        obj = {"a": 1, "b": {"c": [1, 2]}}
        patch = wire.diff_obj(obj, json.loads(json.dumps(obj)))
        assert patch == []
        assert wire.apply_patch(obj, patch) == obj

    def test_type_exact_not_value_equal(self):
        # True == 1 in Python; the wire must still ship the change
        patch = wire.diff_obj({"a": True}, {"a": 1})
        assert patch == [[["a"], 1]]
        assert type(wire.apply_patch({"a": True}, patch)["a"]) is int

    def test_wide_rewrites_fall_back_to_full_frames(self):
        old = {"k%d" % i: i for i in range(40)}
        new = {"k%d" % i: i + 1 for i in range(40)}
        assert wire.diff_obj(old, new) is None
        assert wire.diff_obj(["not"], {"a": 1}) is None

    def test_apply_patch_tolerates_vanished_paths(self):
        # deletes under vanished subtrees are no-ops and sets create the
        # intermediate dicts — structural drift detection is baseRv's
        # job, the patch applier must never crash mid-stream
        base = {"a": {"b": 1}}
        out = wire.apply_patch(base, [[["x", "y"]], [["a", "z"], 5]])
        assert out == {"a": {"b": 1, "z": 5}}
        assert base == {"a": {"b": 1}}   # untouched


# ---------------------------------------------------------------------------
# session frames (version 3: per-stream intern state)
# ---------------------------------------------------------------------------


class TestSessionFrames:
    def test_interns_persist_across_frames(self):
        enc = wire.SessionEncoder()
        ev = {"type": "MODIFIED", "rv": 9,
              "object": {"nodeName": "node-00123", "phase": "Running"}}
        f1, f2 = enc.encode(ev), enc.encode(ev)
        assert len(f2) < len(f1)          # defs went out once, refs after
        assert f2 == enc.encode(ev)       # steady state is stable
        dec = wire.SessionDecoder()
        fp = io.BytesIO(f1 + f2)
        assert wire.read_event(fp, session=dec)[0] == ev
        assert wire.read_event(fp, session=dec)[0] == ev
        # v1 full frames interleave on the same stream (cached WireItem
        # bytes pass through untouched between session frames)
        fp = io.BytesIO(f1 + wire.encode_binary(ev) + enc.encode(ev))
        dec = wire.SessionDecoder()
        got = [wire.read_event(fp, session=dec)[0] for _ in range(3)]
        assert got == [ev, ev, ev]

    def test_session_frame_without_session_is_refused(self):
        frame = wire.SessionEncoder().encode({"a": 1})
        with pytest.raises(wire.WireError):
            wire.read_event(io.BytesIO(frame))
        # and scan() — the WAL replay reader — treats it as torn data,
        # never as a record: session state must NEVER live at rest
        assert wire.scan(frame, 0) is None

    def test_stale_ref_is_an_error_not_garbage(self):
        enc = wire.SessionEncoder()
        enc.encode({"x": "novel-string-abc"})
        f2 = enc.encode({"x": "novel-string-abc"})   # pure refs
        with pytest.raises(wire.WireError):
            wire.read_event(io.BytesIO(f2), session=wire.SessionDecoder())

    def test_negotiation_helpers(self, monkeypatch):
        h = wire.stream_headers()
        assert wire.accept_session(h.get("Accept"))
        assert wire.accept_codec(h.get("Accept")) == wire.BINARY
        assert wire.mime_for(wire.BINARY, session=True) == wire.SESSION_MIME
        assert wire.mime_for(wire.BINARY) == wire.WIRE_MIME
        assert wire.session_of_mime(wire.SESSION_MIME)
        assert not wire.session_of_mime(wire.WIRE_MIME)
        assert not wire.session_of_mime("application/json")
        # a JSON-pinned process offers neither plane on streams
        monkeypatch.setattr(wire, "client_headers", lambda: {})
        assert wire.stream_headers() == {}


# ---------------------------------------------------------------------------
# DELTA records at rest: WAL corruption per the PR-17 CRC contract
# ---------------------------------------------------------------------------


def _delta_wal(tmp_path, n_updates=5):
    """A real server WAL containing DELTA twins: node-update churn where
    each MODIFIED diffs to one small patch. Returns (dir, decoded recs,
    record byte bounds, wal bytes, cpu values per update)."""
    d = str(tmp_path / "state")
    cpus = [4 + i for i in range(n_updates)]
    api = APIServer(data_dir=d)
    api.store.create_node(_node("n0"))
    for c in cpus:
        api.store.update_node(_node("n0", cpu=c))
    api.shutdown()
    buf = (tmp_path / "state" / DurableStore.WAL).read_bytes()
    recs, bounds, pos = [], [0], 0
    while True:
        got = wire.scan(buf, pos)
        if got is None:
            break
        rec, pos = got
        recs.append(rec)
        bounds.append(pos)
    return d, recs, bounds, buf, cpus


class TestDeltaWAL:
    def test_node_churn_lands_as_delta_twins_and_recovers(self, tmp_path):
        d, recs, _bounds, _buf, cpus = _delta_wal(tmp_path)
        deltas = [r for r in recs if r.get("type") == "DELTA"]
        assert len(deltas) >= len(cpus) - 1, [r.get("type") for r in recs]
        for r in deltas:
            assert r["kind"] == "nodes" and r["key"] == "n0"
            assert r["baseRv"] is not None and r["rv"] > r["baseRv"]
            # the at-rest twin is the PATCH, not the object
            assert "object" not in r and r["patch"]
        api2 = APIServer(data_dir=d)
        try:
            assert api2.persistence.torn_records_discarded == 0
            node = api2.store.nodes["n0"]
            assert node.allocatable.milli_cpu == cpus[-1] * 1000
        finally:
            api2.shutdown()

    def test_truncation_mid_delta_record_recovers_clean_prefix(
            self, tmp_path):
        d, recs, bounds, buf, cpus = _delta_wal(tmp_path)
        assert recs[-1].get("type") == "DELTA"
        # cut INSIDE the last record: recovery must land on the previous
        # update's state, with exactly one torn record discarded
        cut = bounds[-2] + 3
        (tmp_path / "state" / DurableStore.WAL).write_bytes(buf[:cut])
        api2 = APIServer(data_dir=d)
        try:
            assert api2.persistence.torn_records_discarded == 1
            node = api2.store.nodes["n0"]
            assert node.allocatable.milli_cpu == cpus[-2] * 1000
        finally:
            api2.shutdown()

    def test_bit_flip_inside_delta_record_quarantines(self, tmp_path):
        from kubernetes_tpu.core.wal import WALQuarantineError
        _d, recs, bounds, buf, _cpus = _delta_wal(tmp_path)
        # pick a MIDDLE record that is a DELTA (never the tail — a
        # damaged tail is legitimately torn, not quarantined)
        idx = next(i for i, r in enumerate(recs[:-1])
                   if r.get("type") == "DELTA")
        start, end = bounds[idx], bounds[idx + 1]
        rng = random.Random(0xF11B)
        for off in sorted(rng.sample(range(start + 4, end), 5)):
            for bit in (1, 0x40):
                damaged = bytearray(buf)
                damaged[off] ^= bit
                d2 = tmp_path / f"flip-{off}-{bit}"
                d2.mkdir()
                (d2 / DurableStore.WAL).write_bytes(bytes(damaged))
                ds = DurableStore(str(d2))
                try:
                    with pytest.raises(WALQuarantineError):
                        ds.load()
                finally:
                    ds.close()

    def test_delta_with_no_recovered_base_quarantines(self, tmp_path):
        """A DELTA whose base never existed in the recovered history is
        damage in the middle of acked state — same class as a CRC miss:
        quarantine, never guess."""
        from kubernetes_tpu.core.wal import WALQuarantineError
        d = str(tmp_path / "ghost")
        ds = DurableStore(d)
        ds.load()
        ds.append({"kind": "nodes", "type": "DELTA", "key": "ghost",
                   "rv": 5, "baseRv": 4, "patch": [[["unschedulable"],
                                                    True]],
                   "seq": 1, "epoch": 1})
        ds.close()
        with pytest.raises(WALQuarantineError):
            APIServer(data_dir=d)


# ---------------------------------------------------------------------------
# delta plane end-to-end: watch streams, fallback, replication, hollow
# ---------------------------------------------------------------------------


class TestDeltaEndToEnd:
    def test_node_churn_rides_delta_frames_to_the_client(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.nodes) == 1, msg="node sync")
            for c in range(9, 19):
                api.store.update_node(_node("n0", cpu=c))
            _wait(lambda: cs.nodes["n0"].allocatable.milli_cpu == 18000,
                  msg="delta convergence")
            assert cs.delta_fallbacks == 0
            assert cs.wire_decode_events[("delta", wire.BINARY)] >= 8
            # delta frames are the small ones: mean delta bytes under
            # mean full bytes even though the FIRST session frame pays
            # the intern defines (steady-state frames are far smaller)
            db = cs.wire_decode_bytes[("delta", wire.BINARY)]
            de = cs.wire_decode_events[("delta", wire.BINARY)]
            fb = cs.wire_decode_bytes[("full", wire.BINARY)]
            fe = cs.wire_decode_events[("full", wire.BINARY)]
            assert db / de < fb / fe, (cs.wire_decode_bytes,
                                       cs.wire_decode_events)
            # server-side attribution
            minted = sum(wc.deltas_minted
                         for wc in api.watch_cache.values())
            assert minted >= 8
            assert "apiserver_wire_deltas_minted_total" in \
                api.expose_metrics()
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_base_rv_mismatch_falls_back_to_relist_not_divergence(self):
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.nodes) == 1, msg="node sync")
            api.store.update_node(_node("n0", cpu=9))
            _wait(lambda: cs.nodes["n0"].allocatable.milli_cpu == 9000,
                  msg="first delta")
            # sabotage the client's recorded base rv: the NEXT delta's
            # baseRv cannot match, so the one legal answer is a re-list
            for k in list(cs._wire_rv["nodes"]):
                cs._wire_rv["nodes"][k] = 999_999_999
            api.store.update_node(_node("n0", cpu=11))
            _wait(lambda: cs.delta_fallbacks >= 1, msg="fallback")
            _wait(lambda: cs.nodes["n0"].allocatable.milli_cpu == 11000,
                  msg="relist convergence")
            # and the stream keeps working afterwards — deltas resume
            # against the fresh base
            api.store.update_node(_node("n0", cpu=13))
            _wait(lambda: cs.nodes["n0"].allocatable.milli_cpu == 13000,
                  msg="post-fallback delta")
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    def test_json_pinned_client_never_sees_delta_frames(self, monkeypatch):
        monkeypatch.setattr(wire, "client_headers", lambda: {})
        api = APIServer()
        port = api.serve(0)
        cs = None
        try:
            api.store.create_node(_node("n0"))
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            _wait(lambda: len(cs.nodes) == 1, msg="node sync")
            for c in range(9, 14):
                api.store.update_node(_node("n0", cpu=c))
            _wait(lambda: cs.nodes["n0"].allocatable.milli_cpu == 13000,
                  msg="json convergence")
            assert cs.wire_decode_events[("delta", wire.JSON)] == 0
            assert cs.wire_decode_events[("delta", wire.BINARY)] == 0
            assert cs.delta_fallbacks == 0
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()


class TestDeltaReplication:
    def test_follower_materializes_shipped_deltas(self):
        leader = APIServer()
        lport = leader.serve(0)
        follower = APIServer()
        tail = ReplicationTail(follower, f"http://127.0.0.1:{lport}",
                               rank=1, lease_duration=5.0)
        try:
            leader.store.create_node(_node("n0"))
            tail.bootstrap()
            tail.start()
            _wait(lambda: len(follower.store.nodes) == 1, msg="bootstrap")
            for c in range(9, 19):
                leader.store.update_node(_node("n0", cpu=c))
            _wait(lambda: follower.store.nodes["n0"]
                  .allocatable.milli_cpu == 18000, msg="delta tail")
            assert tail.delta_resyncs == 0
            applied = sum(wc.deltas_applied
                          for wc in follower.watch_cache.values())
            assert applied >= 8
            # zero divergence: the follower's wire object for the node is
            # bit-identical to the leader's (the invariant every DELTA
            # materialization depends on)
            lw = leader.watch_cache["nodes"]._objects["n0"]
            fw = follower.watch_cache["nodes"]._objects["n0"]
            assert lw == fw
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()

    def test_base_mismatch_snapshot_resyncs_and_promotes_clean(self):
        leader = APIServer()
        lport = leader.serve(0)
        follower = APIServer()
        tail = ReplicationTail(follower, f"http://127.0.0.1:{lport}",
                               rank=1, lease_duration=0.5)
        fport = follower.serve(0)
        follower.repl_peers.update(
            {0: f"http://127.0.0.1:{lport}", 1: f"http://127.0.0.1:{fport}"})
        try:
            leader.store.create_node(_node("n0"))
            leader.store.create_pod(_pod("p0"))
            tail.bootstrap()
            tail.start()
            _wait(lambda: len(follower.store.nodes) == 1
                  and len(follower.store.pods) == 1, msg="bootstrap")
            # sabotage the follower's recorded base rv: the next shipped
            # DELTA raises DeltaBaseMismatch out of apply_frame and the
            # tail answers with a full snapshot resync — a patch is never
            # applied onto a divergent base
            # (keyed off _objects: after a snapshot bootstrap _obj_rv is
            # empty by design — unknown rvs take the accept-if-unknown
            # path, so a poisoned rv must be INSTALLED, not overwritten)
            wc = follower.watch_cache["nodes"]
            with wc._lock:
                for k in list(wc._objects):
                    wc._obj_rv[k] = 999_999_999
            leader.store.update_node(_node("n0", cpu=9))
            _wait(lambda: tail.delta_resyncs >= 1, msg="resync")
            _wait(lambda: follower.store.nodes["n0"]
                  .allocatable.milli_cpu == 9000, msg="resync converged")
            # stream stays live after the resync, deltas included
            leader.store.update_node(_node("n0", cpu=12))
            _wait(lambda: follower.store.nodes["n0"]
                  .allocatable.milli_cpu == 12000, msg="post-resync tail")
            assert leader.watch_cache["nodes"]._objects["n0"] == \
                follower.watch_cache["nodes"]._objects["n0"]
            # and promotion carries the materialized state forward
            old_epoch = follower.repl_epoch
            leader.shutdown()
            _wait(lambda: follower.role == "leader", timeout=20.0,
                  msg="promotion")
            assert follower.repl_epoch > old_epoch
            assert follower.store.nodes["n0"].allocatable.milli_cpu == 12000
            follower.store.create_pod(_pod("p-after"))
            assert len(follower.store.pods) == 2
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()


class TestHollowHeartbeatBody:
    def test_bulk_heartbeats_ride_the_negotiated_binary_codec(self):
        from kubernetes_tpu.hollow import HollowNodePlane, HollowProfile
        api = APIServer()
        port = api.serve(0)
        plane = None
        try:
            prof = HollowProfile(count=40, zones=4, heartbeat_s=0.3,
                                 drift=0.0, churn_per_s=0.0,
                                 register_chunk=20)
            plane = HollowNodePlane(f"http://127.0.0.1:{port}", prof)
            assert plane.register() == 40
            plane.start()
            _wait(lambda: plane.heartbeats >= 80,
                  msg="two heartbeat sweeps")
            # the POST bodies were counted on the server's status surface,
            # on the binary plane, and the plane saw a wire-speaking server
            assert api.wire_bytes[("binary", "status")] > 0, api.wire_bytes
            assert plane.hb_wire_posts > 0
            assert plane.stats()["hb_wire_posts"] == plane.hb_wire_posts
        finally:
            if plane is not None:
                plane.stop()
            api.shutdown()


# ---------------------------------------------------------------------------
# the tier-1 encode-path guard: delta must beat full binary (and ride
# below the C-json baseline measured in the SAME run)
# ---------------------------------------------------------------------------


class TestDeltaEncodeGuard:
    def test_delta_encode_beats_full_binary_on_heartbeat_corpus(self):
        from kubernetes_tpu.wire import encode_ab
        ab = encode_ab(1500)
        hb = ab["corpora"]["heartbeat"]
        assert hb["binary_delta"]["encode_us"] <= \
            hb["binary_full"]["encode_us"], ab
        # the frames themselves: ≥5× smaller than the full binary frame
        # on both churn corpora (the size win is deterministic)
        for name in ("heartbeat", "drift"):
            row = ab["corpora"][name]
            assert row["delta_vs_full_bytes"] >= 5.0, ab
