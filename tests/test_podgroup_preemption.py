"""Pod-group preemption (preemption/podgrouppreemption.go PodGroupEvaluator
via the PodGroupPostFilter extension point) and async victim deletion
(executor.go:171 prepareCandidateAsync via the APIDispatcher)."""

from kubernetes_tpu.api.types import PodGroup
from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.config import SchedulerConfiguration
from kubernetes_tpu.core.registry import gang_placement_profiles
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _full_cluster(cs, n_nodes=4, cpu=4, fill_prio=1):
    """n nodes, each filled by one low-priority 4-cpu pod."""
    filler = []
    for i in range(n_nodes):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110})
                       .zone(f"z{i % 2}").obj())
    for i in range(n_nodes):
        p = make_pod().name(f"low-{i}").req({"cpu": str(cpu)}).priority(fill_prio).obj()
        p.node_name = f"n{i}"
        cs.create_pod(p)
        filler.append(p)
    return filler


class TestPodGroupPreemption:
    def test_gang_preempts_enough_victims(self):
        cs = FakeClientset()
        s = Scheduler(clientset=cs, profile_factory=gang_placement_profiles,
                      deterministic_ties=True)
        filler = _full_cluster(cs, n_nodes=4)
        cs.create_pod_group(PodGroup(name="train", min_count=2))
        gang = []
        for i in range(2):
            p = make_pod().name(f"hi-{i}").req({"cpu": "4"}).priority(100).obj()
            p.pod_group = "train"
            cs.create_pod(p)
            gang.append(p)
        s.run_until_idle()
        # Exactly 2 victims evicted (reprieve keeps the other 2), gang bound.
        assert sum(1 for p in filler if p.uid not in cs.pods) == 2
        assert all(p.node_name for p in gang), [p.node_name for p in gang]

    def test_no_preemption_for_lower_priority_gang(self):
        cs = FakeClientset()
        s = Scheduler(clientset=cs, profile_factory=gang_placement_profiles,
                      deterministic_ties=True)
        filler = _full_cluster(cs, n_nodes=2, fill_prio=50)
        cs.create_pod_group(PodGroup(name="train", min_count=2))
        for i in range(2):
            p = make_pod().name(f"lo-{i}").req({"cpu": "4"}).priority(10).obj()
            p.pod_group = "train"
            cs.create_pod(p)
        s.run_until_idle()
        assert all(p.uid in cs.pods for p in filler)  # nobody evicted
        assert s.scheduled == 0

    def test_placement_constrained_gang_preempts_within_domain(self):
        cs = FakeClientset()
        s = Scheduler(clientset=cs, profile_factory=gang_placement_profiles,
                      deterministic_ties=True)
        filler = _full_cluster(cs, n_nodes=4)
        cs.create_pod_group(PodGroup(name="train", min_count=2,
                                     topology_keys=(ZONE,)))
        gang = []
        for i in range(2):
            p = make_pod().name(f"hi-{i}").req({"cpu": "4"}).priority(100).obj()
            p.pod_group = "train"
            cs.create_pod(p)
            gang.append(p)
        s.run_until_idle()
        assert all(p.node_name for p in gang)
        zones = {cs.nodes[p.node_name].labels[ZONE] for p in gang}
        assert len(zones) == 1  # preempted AND packed into one zone


class TestAsyncPreemption:
    def test_victims_deleted_through_thread_dispatcher(self):
        cfg = SchedulerConfiguration(async_dispatch_threads=True)
        cs = FakeClientset()
        s = Scheduler(clientset=cs, config=cfg, deterministic_ties=True)
        assert s.api_dispatcher.mode == "thread"
        for i in range(2):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        low = []
        for i in range(2):
            p = make_pod().name(f"low-{i}").req({"cpu": "4"}).priority(1).obj()
            p.node_name = f"n{i}"
            cs.create_pod(p)
            low.append(p)
        hi = make_pod().name("hi").req({"cpu": "4"}).priority(100).obj()
        cs.create_pod(hi)
        s.run_until_idle()
        s.api_dispatcher.flush()
        s.run_until_idle()
        assert hi.node_name, (s.error_log, hi.nominated_node_name)
        assert sum(1 for p in low if p.uid not in cs.pods) == 1
        s.api_dispatcher.close()
