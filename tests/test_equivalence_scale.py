"""Equivalence at claimed scale (round-2 verdict #7): a 5k-node run, a wide
mixed-feature fuzz corpus, LAP_MAX window spill under custom
percentageOfNodesToScore, host/device interleaving divergence, and
kill-and-rebuild-mid-workload recovery."""

import random

import pytest

from kubernetes_tpu.core import FakeClientset
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.ops.kernel import LAP_MAX
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


def _assignments(cs):
    return {p.name: p.node_name for p in cs.pods.values()}


def _mk_nodes(cs, n, zones=8, seed=0):
    rng = random.Random(seed)
    for i in range(n):
        cs.create_node(make_node().name(f"node-{i}")
                       .capacity({"cpu": rng.choice([8, 16, 32]),
                                  "memory": "64Gi", "pods": 110})
                       .zone(f"zone-{i % zones}")
                       .label("disk", rng.choice(["ssd", "hdd"])).obj())


class TestLargeScale:
    def test_5k_nodes_identical_assignments(self):
        """5k nodes (the BASELINE scale), mixed spread + plain pods, with a
        custom percentageOfNodesToScore=1 so each lap spans >LAP_MAX windows
        (kernel.py LAP_MAX spill: to_find=100, ~can't cover 5k feasible rows
        in one 32-window lap)."""
        def build(cls):
            cs = FakeClientset()
            kw = dict(percentage_of_nodes_to_score=1)
            if cls is TPUScheduler:
                s = cls(clientset=cs, **kw)
            else:
                s = cls(clientset=cs, deterministic_ties=True, **kw)
            _mk_nodes(cs, 5000, zones=50)
            pods = []
            for i in range(200):
                pods.append(make_pod().name(f"plain-{i}").req({"cpu": "100m"}).obj())
            for i in range(100):
                pods.append(make_pod().name(f"spread-{i}").req({"cpu": "100m"})
                            .labels({"app": "s"})
                            .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "s"}).obj())
            for p in pods:
                cs.create_pod(p)
            s.run_until_idle()
            return cs, s
        cs_h, s_h = build(Scheduler)
        cs_d, s_d = build(TPUScheduler)
        assert s_h.scheduled == s_d.scheduled == 300
        # the custom percentage makes feasible//to_find exceed LAP_MAX,
        # exercising the spill path
        assert 5000 * 90 // 100 // max(
            1, 5000 * 1 // 100) > LAP_MAX or 100 < LAP_MAX  # sanity on intent
        diffs = {k: (v, _assignments(cs_d).get(k))
                 for k, v in _assignments(cs_h).items()
                 if v != _assignments(cs_d).get(k)}
        assert not diffs, f"{len(diffs)} diverged: {dict(list(diffs.items())[:4])}"


class TestWideFuzz:
    @pytest.mark.parametrize("seed", range(50))
    def test_mixed_feature_fuzz(self, seed):
        """50 seeds over clusters ≤56 nodes (one np_cap tier, so the compile
        cache amortizes) with every device-covered feature in the mix."""
        rng = random.Random(7000 + seed)
        n_nodes = rng.randint(6, 56)

        def build(cls):
            cs = FakeClientset()
            s = (TPUScheduler(clientset=cs, max_batch=64)
                 if cls is TPUScheduler
                 else Scheduler(clientset=cs, deterministic_ties=True))
            rng_n = random.Random(100 + seed)
            for i in range(n_nodes):
                b = (make_node().name(f"node-{i}")
                     .capacity({"cpu": rng_n.choice([4, 8, 16]),
                                "memory": f"{rng_n.choice([16, 32])}Gi",
                                "pods": 110})
                     .zone(f"zone-{i % rng_n.randint(2, 5)}")
                     .label("disk", rng_n.choice(["ssd", "hdd"])))
                if rng_n.random() < 0.15:
                    b = b.taint("dedicated", "infra", "NoSchedule")
                if rng_n.random() < 0.2:
                    b = b.image("app:v1", 500 * 1024 * 1024)
                cs.create_node(b.obj())
            rng_p = random.Random(200 + seed)
            pods = []
            for d in range(rng_p.randint(1, 4)):
                labels = {"app": f"d{d}"}
                kind = rng_p.random()
                for i in range(rng_p.randint(2, 10)):
                    b = (make_pod().name(f"d{d}-{i}")
                         .req({"cpu": rng_p.choice(["100m", "500m", "2"]),
                               "memory": rng_p.choice(["64Mi", "1Gi"])})
                         .labels(dict(labels)))
                    if kind < 0.2:
                        b = b.spread_constraint(
                            rng_p.choice([1, 2]), ZONE,
                            rng_p.choice(["DoNotSchedule", "ScheduleAnyway"]), labels)
                    elif kind < 0.35:
                        b = b.pod_affinity(HOSTNAME, labels, anti=True)
                    elif kind < 0.45:
                        b = b.pod_affinity(ZONE, labels,
                                           weight=rng_p.choice([0, 5]))
                    elif kind < 0.55:
                        b = b.node_affinity_in("disk", ["ssd"])
                    elif kind < 0.62:
                        b = b.preferred_node_affinity(7, "disk", ["hdd"])
                    elif kind < 0.70:
                        b = b.host_port(8080 + d)
                    elif kind < 0.78:
                        b = b.image("app:v1")
                    elif kind < 0.85:
                        b = b.toleration("dedicated", "infra", "Equal", "NoSchedule")
                    pods.append(b.obj())
            for p in pods:
                cs.create_pod(p)
            s.run_until_idle()
            return cs, s

        cs_h, s_h = build(Scheduler)
        cs_d, s_d = build(TPUScheduler)
        a_h, a_d = _assignments(cs_h), _assignments(cs_d)
        diffs = {k: (a_h[k], a_d.get(k)) for k in a_h if a_h[k] != a_d.get(k)}
        assert not diffs, f"seed {seed}: {dict(list(diffs.items())[:4])}"
        assert s_h.scheduled == s_d.scheduled


class TestInterleavingAndRecovery:
    def test_host_device_interleaving(self):
        """Unsupported pods (PVC-backed → host path) interleaved with device
        batches force repeated session invalidations; assignments must still
        match the pure-host oracle."""
        from kubernetes_tpu.api.types import Volume

        def build(cls):
            cs = FakeClientset()
            s = (TPUScheduler(clientset=cs, max_batch=16)
                 if cls is TPUScheduler
                 else Scheduler(clientset=cs, deterministic_ties=True))
            _mk_nodes(cs, 24, zones=4)
            for i in range(60):
                p = make_pod().name(f"p-{i}").req({"cpu": "200m"}).obj()
                if i % 7 == 3:
                    p.nominated_node_name = ""  # plain marker; keep device
                if i % 5 == 2:
                    p.volumes.append(Volume(name="data", pvc_name=f"missing-{i}"))
                cs.create_pod(p)
            s.run_until_idle()
            return cs, s
        cs_h, s_h = build(Scheduler)
        cs_d, s_d = build(TPUScheduler)
        assert s_d.host_path_pods > 0  # interleaving actually happened
        assert _assignments(cs_h) == _assignments(cs_d)
        assert s_h.scheduled == s_d.scheduled
        assert s_h.failures == s_d.failures  # missing-PVC pods fail identically

    def test_kill_and_rebuild_mid_workload(self):
        """The scheduler is stateless (SURVEY §5 failure recovery): kill the
        TPUScheduler after half the workload, build a fresh one against the
        same clientset (re-list), finish, and match a host pair restarted at
        the same point — cache, queue, AND device mirror all rebuild."""
        def build(cls):
            cs = FakeClientset()
            first = (TPUScheduler(clientset=cs, max_batch=16)
                     if cls is TPUScheduler
                     else Scheduler(clientset=cs, deterministic_ties=True))
            _mk_nodes(cs, 30, zones=3)
            for i in range(40):
                cs.create_pod(make_pod().name(f"a-{i}").req({"cpu": "250m"})
                              .labels({"app": "a"})
                              .spread_constraint(1, ZONE, "DoNotSchedule",
                                                 {"app": "a"}).obj())
            first.run_until_idle()
            assert first.scheduled == 40
            # "kill" the first scheduler; a fresh instance re-lists from the
            # clientset (informer resync): bound pods land in its cache.
            second = (TPUScheduler(clientset=cs, max_batch=16)
                      if cls is TPUScheduler
                      else Scheduler(clientset=cs, deterministic_ties=True))
            for node in list(cs.nodes.values()):
                second._on_node_event("add", None, node)
            for p in list(cs.pods.values()):
                second._on_pod_event("add", None, p)
            for i in range(40):
                cs.create_pod(make_pod().name(f"b-{i}").req({"cpu": "250m"})
                              .labels({"app": "a"})
                              .spread_constraint(1, ZONE, "DoNotSchedule",
                                                 {"app": "a"}).obj())
            second.run_until_idle()
            assert second.scheduled == 40
            return cs
        cs_h = build(Scheduler)
        cs_d = build(TPUScheduler)
        assert _assignments(cs_h) == _assignments(cs_d)


class TestAuxConstraintFuzz:
    """Randomized equivalence over the counted-constraint (aux) paths new in
    round 4: bound-PVC pods under random CSI attach limits and DRA
    claim-template pods over random device pools, interleaved with plain
    pods — assignments must equal the host oracle on every seed."""

    @pytest.mark.parametrize("seed", range(8))
    def test_csi_and_dra_aux_fuzz(self, seed):
        from kubernetes_tpu.api.dra import Device, DeviceRequest, ResourceClaim, ResourceSlice
        from kubernetes_tpu.api.storage import CSINode, PersistentVolume, PersistentVolumeClaim
        from kubernetes_tpu.api.types import Volume
        from kubernetes_tpu.core.registry import DEFAULT_PLUGINS, build_framework

        rng = random.Random(1000 + seed)
        n_nodes = rng.randint(6, 16)
        limit = rng.randint(1, 3)
        devs_per_node = rng.randint(1, 3)
        n_vol = rng.randint(3, 3 * n_nodes)
        n_dra = rng.randint(3, devs_per_node * n_nodes + 4)
        n_plain = rng.randint(0, 10)

        def build(cls):
            cs = FakeClientset()
            plugins = DEFAULT_PLUGINS + (("DynamicResources", 0),)
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            s = cls(clientset=cs, profile_factory=lambda h: {
                "default-scheduler": build_framework(h, plugins=plugins)}, **kw)
            for i in range(n_nodes):
                cs.create_node(make_node().name(f"n{i}")
                               .capacity({"cpu": 64, "memory": "256Gi",
                                          "pods": 110}).obj())
                cs.create_csi_node(CSINode(node_name=f"n{i}",
                                           driver_limits={"csi.x": limit}))
                cs.create_resource_slice(ResourceSlice(
                    node_name=f"n{i}", driver="gpu.x",
                    devices=[Device(name=f"n{i}-d{j}",
                                    attributes={"model": "a100"})
                             for j in range(devs_per_node)]))
            pods = []
            for i in range(n_vol):
                pv = PersistentVolume.of(f"pv-{i}", "1Gi",
                                         access_modes=("ReadOnlyMany",),
                                         csi_driver="csi.x")
                pvc = PersistentVolumeClaim.of(f"pvc-{i}", "1Gi",
                                               access_modes=("ReadOnlyMany",))
                pv.claim_ref = pvc.key
                pvc.volume_name = pv.name
                cs.create_pv(pv)
                cs.create_pvc(pvc)
                p = make_pod().name(f"vol-{i}").req({"cpu": "100m"}).obj()
                p.volumes.append(Volume(name="d", pvc_name=f"pvc-{i}"))
                pods.append(p)
            for i in range(n_dra):
                cs.create_resource_claim(ResourceClaim(
                    name=f"c{i}", requests=[DeviceRequest(
                        name="r", count=1,
                        expression='device.attributes["model"] == "a100"')]))
                p = make_pod().name(f"dra-{i}").req({"cpu": "100m"}).obj()
                p.resource_claims = [f"c{i}"]
                pods.append(p)
            for i in range(n_plain):
                pods.append(make_pod().name(f"plain-{i}")
                            .req({"cpu": "200m"}).obj())
            rng2 = random.Random(seed)
            rng2.shuffle(pods)
            for p in pods:
                cs.create_pod(p)
            s.run_until_idle()
            return cs, s

        cs_h, _ = build(Scheduler)
        cs_d, dev = build(TPUScheduler)
        h = _assignments(cs_h)
        d = _assignments(cs_d)
        assert h == d, {k: (h[k], d[k]) for k in h if h[k] != d.get(k)}


class TestGangFuzz:
    """Randomized gangs: flat + topology-constrained groups of random sizes
    interleaved with plain pods, device (gang sessions + stacked placement
    evaluation) vs host oracle."""

    @pytest.mark.parametrize("seed", range(6))
    def test_mixed_gang_fuzz(self, seed):
        from kubernetes_tpu.api.types import PodGroup
        from kubernetes_tpu.core.registry import gang_placement_profiles

        rng = random.Random(2000 + seed)
        n_nodes = rng.randint(8, 24)
        zones = rng.randint(2, 4)
        n_flat = rng.randint(0, 4)
        n_topo = rng.randint(0, 3)
        n_plain = rng.randint(0, 8)

        def build(cls):
            cs = FakeClientset()
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            s = cls(clientset=cs, profile_factory=gang_placement_profiles, **kw)
            for i in range(n_nodes):
                cs.create_node(make_node().name(f"n{i}")
                               .capacity({"cpu": rng_caps[i],
                                          "memory": "64Gi", "pods": 110})
                               .zone(f"z{i % zones}").obj())
            pods = []
            for g in range(n_flat):
                size = flat_sizes[g]
                cs.create_pod_group(PodGroup(name=f"fg{g}", min_count=size))
                for j in range(size):
                    p = make_pod().name(f"fg{g}-{j}").req({"cpu": "500m"}).obj()
                    p.pod_group = f"fg{g}"
                    pods.append(p)
            for g in range(n_topo):
                size = topo_sizes[g]
                cs.create_pod_group(PodGroup(name=f"tg{g}", min_count=size,
                                             topology_keys=(ZONE,)))
                for j in range(size):
                    p = make_pod().name(f"tg{g}-{j}").req({"cpu": "250m"}).obj()
                    p.pod_group = f"tg{g}"
                    pods.append(p)
            for i in range(n_plain):
                pods.append(make_pod().name(f"pl-{i}").req({"cpu": "200m"}).obj())
            rng2 = random.Random(seed)
            rng2.shuffle(pods)
            for p in pods:
                cs.create_pod(p)
            s.run_until_idle()
            return cs, s

        rng_caps = [rng.choice([4, 8, 16]) for _ in range(n_nodes)]
        flat_sizes = [rng.randint(2, 5) for _ in range(n_flat)]
        topo_sizes = [rng.randint(2, 4) for _ in range(n_topo)]

        cs_h, _ = build(Scheduler)
        cs_d, _ = build(TPUScheduler)
        h = _assignments(cs_h)
        d = _assignments(cs_d)
        assert h == d, {k: (h[k], d[k]) for k in h if h[k] != d.get(k)}
