"""Timer-driven Permit WAIT expiry (runtime/framework.go:2097), slow-step
tracing (schedule_one.go:574), and the event recorder (schedule_one.go:1138)."""

import logging

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.framework import OK, Status, WAIT
from kubernetes_tpu.core.registry import DEFAULT_PLUGINS, build_framework
from kubernetes_tpu.core.tracing import StepTrace
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class ParkOnce:
    """Permit plugin: WAIT the first pod forever (nobody allows it)."""

    name = "ParkOnce"

    def __init__(self):
        self.parked = []

    def permit(self, state, pod, node_name):
        if not self.parked:
            self.parked.append(pod.uid)
            return Status(WAIT, ("parked",), self.name)
        return OK


def test_permit_timeout_fires_under_continuous_load():
    """A parked pod must time out WHILE the scheduler stays busy — no idle
    moment ever happens (round-2 verdict: expiry was idle-poll-driven)."""
    clock = [0.0]
    parker = ParkOnce()

    def factory(h):
        fw = build_framework(h)
        fw.permit_plugins.append(parker)
        return {"default-scheduler": fw}

    cs = FakeClientset()
    s = Scheduler(clientset=cs, profile_factory=factory,
                  deterministic_ties=True, now=lambda: clock[0])
    s.permit_wait_timeout = 30.0
    for i in range(4):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": 64, "memory": "256Gi", "pods": 500}).obj())
    cs.create_pod(make_pod().name("parked").req({"cpu": "100m"}).obj())
    assert s.schedule_one()
    assert len(s.waiting_pods) == 1

    # Continuous load: one new pod per tick, clock advancing past the
    # deadline — the queue NEVER goes empty between cycles.
    for i in range(40):
        clock[0] += 1.0
        cs.create_pod(make_pod().name(f"busy-{i}").req({"cpu": "100m"}).obj())
        s.schedule_one()
    assert not s.waiting_pods, "parked pod never timed out under load"
    parked = cs.pods[parker.parked[0]]
    assert not parked.node_name  # rejected, not bound
    evs = s.recorder.for_object(f"{parked.namespace}/{parked.name}")
    assert any(e.reason == "FailedScheduling" for e in evs)


def test_scheduled_events_recorded():
    cs = FakeClientset()
    s = Scheduler(clientset=cs, deterministic_ties=True)
    cs.create_node(make_node().name("n0").capacity(
        {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
    cs.create_pod(make_pod().name("p0").req({"cpu": "1"}).obj())
    s.run_until_idle()
    evs = s.recorder.for_object("default/p0")
    assert any(e.reason == "Scheduled" and "n0" in e.message for e in evs)


def test_slow_step_trace_logs(caplog):
    tr = StepTrace("Scheduling", pod="default/slow")
    tr.t0 -= 0.5  # pretend the cycle took 500ms
    tr._last = tr.t0
    tr.step("scheduling cycle done")
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu"):
        total = tr.log_if_long()
    assert total > 0.4
    assert any("slow scheduling step" in r.message for r in caplog.records)
    assert any("default/slow" in r.getMessage() for r in caplog.records)
