"""Static-analysis guard for the s64/s32 GSPMD regression class.

ROADMAP (resolved, PR 2): under x64, a bare `jnp.arange` (or any index
producer defaulting to int64) fed into scatter/gather index tuples mixes
s64 indices with the GSPMD partitioner's s32 offset math, and this
environment's XLA miscompiles the comparison ("compare(s64, s32) after
spmd-partitioning"). The fix pinned every index producer in
kubernetes_tpu/ops/ to an explicit int32. This test scans the ops sources
so the fix cannot silently regress: every `jnp.arange(` must carry an
explicit dtype, and argmax/argsort-style index producers must cast to
int32 in the same statement. Deliberate int64 quantity math goes on the
allowlist below with a reason.
"""

from __future__ import annotations

import pathlib
import re

_PKG = pathlib.Path(__file__).resolve().parent.parent / "kubernetes_tpu"
OPS_DIR = _PKG / "ops"


def _scanned_files():
    """Every source whose jnp index producers can reach a device kernel:
    all of ops/, plus models/tpu_scheduler.py — its session orchestration
    builds scatter/gather operands too (victim tensors, placement masks,
    delta-patch row vectors), so the s64/s32 GSPMD miscompile class can
    regress from there just as well as from ops/."""
    return sorted(OPS_DIR.glob("*.py")) + [
        _PKG / "models" / "tpu_scheduler.py"]


# (file name, 1-based line of the producer) -> reason. Quantity math that
# genuinely needs int64 (resource units exceed int32) belongs here, never
# anything whose result indexes a scatter/gather.
ALLOWLIST: dict = {}


def _call_text(src: str, open_paren: int) -> str:
    """Source text of one call: from its opening paren to the matching
    close (string-literal-naive is fine for this codebase's ops files)."""
    depth = 0
    for i in range(open_paren, len(src)):
        if src[i] == "(":
            depth += 1
        elif src[i] == ")":
            depth -= 1
            if depth == 0:
                return src[open_paren:i + 1]
    return src[open_paren:]


def _statement_text(src: str, pos: int) -> str:
    """The logical statement around `pos`: its line plus continuation lines
    while parens stay open (enough context to see an .astype cast)."""
    start = src.rfind("\n", 0, pos) + 1
    end = src.find("\n", pos)
    stmt = src[start:end if end >= 0 else len(src)]
    while stmt.count("(") > stmt.count(")") and end >= 0:
        nxt = src.find("\n", end + 1)
        stmt += src[end:nxt if nxt >= 0 else len(src)]
        end = nxt
    return stmt


def test_ops_jnp_arange_pins_dtype():
    """Every jnp.arange in ops/ must pass an explicit dtype (bare arange
    defaults to int64 under x64 and these values feed index operands)."""
    bad = []
    for path in _scanned_files():
        src = path.read_text()
        for m in re.finditer(r"jnp\.arange\(", src):
            line = src.count("\n", 0, m.start()) + 1
            if (path.name, line) in ALLOWLIST:
                continue
            call = _call_text(src, m.end() - 1)
            if "dtype=" not in call:
                bad.append(f"{path.name}:{line}: jnp.arange without dtype")
    assert not bad, (
        "index producers without an explicit dtype (s64/s32 GSPMD "
        "miscompile class — pin int32 or allowlist with a reason):\n"
        + "\n".join(bad))


def test_ops_argmax_style_producers_cast_int32():
    """argmax/argsort/nonzero-style jnp index producers must cast to int32
    in the same statement (their int64 default rides into index tuples)."""
    bad = []
    producers = r"jnp\.(argmax|argmin|argsort|nonzero|searchsorted)\("
    for path in _scanned_files():
        src = path.read_text()
        for m in re.finditer(producers, src):
            line = src.count("\n", 0, m.start()) + 1
            if (path.name, line) in ALLOWLIST:
                continue
            stmt = _statement_text(src, m.start())
            if "int32" not in stmt:
                bad.append(f"{path.name}:{line}: {m.group(0)}... "
                           "without an int32 cast in the statement")
    assert not bad, (
        "argmax-style index producers without int32 pinning:\n"
        + "\n".join(bad))


def test_ops_scatter_index_asarray_pins_dtype():
    """jnp.asarray calls that build scatter/gather index vectors (named
    idx/rows/dirty) must pass an explicit int32 dtype."""
    bad = []
    pat = re.compile(r"jnp\.asarray\((?:sorted\()?(?:dirty|rows_idx|prows|"
                     r"dirty_rows|idx)\b[^)]*\)")
    for path in _scanned_files():
        src = path.read_text()
        for m in re.finditer(pat, src):
            line = src.count("\n", 0, m.start()) + 1
            if (path.name, line) in ALLOWLIST:
                continue
            if "int32" not in m.group(0):
                bad.append(f"{path.name}:{line}: {m.group(0)}")
    assert not bad, ("index-vector asarray without int32 dtype:\n"
                     + "\n".join(bad))
