"""Static-analysis guard for the s64/s32 GSPMD regression class.

ROADMAP (resolved, PR 2): under x64, a bare `jnp.arange` (or any index
producer defaulting to int64) fed into scatter/gather index tuples mixes
s64 indices with the GSPMD partitioner's s32 offset math, and this
environment's XLA miscompiles the comparison ("compare(s64, s32) after
spmd-partitioning").

PR 7 ported the original regex scan onto the AST checker
`kubernetes_tpu.analysis.index_dtype` (which also fixed the old
`_call_text` helper's string-literal-naive paren matching — the AST sees
real call structure, not characters) and widened the scope from ops/ +
models/tpu_scheduler.py to the whole package. This file stays as a thin
runner so the historical test IDs keep gating tier-1; deliberate int64
quantity math goes on `kubernetes_tpu/analysis/allowlist.py` with a
mandatory reason, never here.
"""

from __future__ import annotations

import functools

from kubernetes_tpu.analysis import analyze
from kubernetes_tpu.analysis.index_dtype import IndexDtypeChecker


@functools.lru_cache(maxsize=1)
def _report():
    # One tree scan shared by the three test IDs (the scan re-parses the
    # whole package; the result is deterministic within a run).
    return analyze(checkers=[IndexDtypeChecker()])


def _findings(rule: str):
    return [str(f) for f in _report().findings if f.rule == rule]


def test_ops_jnp_arange_pins_dtype():
    """Every jnp.arange in the package must pass an explicit dtype (bare
    arange defaults to int64 under x64 and these values feed index
    operands)."""
    bad = _findings("arange-dtype")
    assert not bad, (
        "index producers without an explicit dtype (s64/s32 GSPMD "
        "miscompile class — pin int32 or allowlist with a reason):\n"
        + "\n".join(bad))


def test_ops_argmax_style_producers_cast_int32():
    """argmax/argsort/nonzero-style jnp index producers must cast to int32
    in the same statement (their int64 default rides into index tuples)."""
    bad = _findings("argmax-cast")
    assert not bad, (
        "argmax-style index producers without int32 pinning:\n"
        + "\n".join(bad))


def test_ops_scatter_index_asarray_pins_dtype():
    """jnp.asarray calls that build scatter/gather index vectors (named
    idx/rows/dirty/...) must pass an explicit int32 dtype."""
    bad = _findings("asarray-index-dtype")
    assert not bad, ("index-vector asarray without int32 dtype:\n"
                     + "\n".join(bad))
