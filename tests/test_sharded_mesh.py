"""Multi-chip sharding in the PRODUCTION path: with >1 device visible (the
8-device virtual CPU mesh in conftest), TPUScheduler automatically shards
the node axis over a ("cells", "nodes") mesh and the kernel compiles SPMD —
every test in test_device_equivalence.py therefore runs sharded≡host. These
tests pin the activation so it cannot silently regress to single-device.

The two SPMD-asserting tests (chained sessions, multihost mesh) are live
again: the environment's GSPMD s64/s32 miscompile was fixed at the source
(uniform-int32 scan index/carry in ops/kernel.py — see ROADMAP), so a
breaker-driven fallback to the host path here is a REGRESSION, not an
environment fact."""

import jax
import numpy as np

from kubernetes_tpu.core import FakeClientset
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def test_mesh_auto_activates_with_multiple_devices():
    assert len(jax.devices()) == 8, "conftest must provide the virtual mesh"
    s = TPUScheduler()
    assert s.mesh is not None
    assert dict(s.mesh.shape) == {"cells": 1, "nodes": 8}


def test_state_actually_sharded_across_devices():
    cs = FakeClientset()
    s = TPUScheduler(clientset=cs)
    for i in range(40):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                       .zone(f"z{i % 4}").obj())
    for i in range(16):
        cs.create_pod(make_pod().name(f"p{i}").req({"cpu": "500m"}).obj())
    s.run_until_idle()
    assert s.scheduled == 16 and s.host_path_pods == 0
    fw = s.framework_for_pod(make_pod().name("probe").req({"cpu": "1"}).obj())
    state, plan = s.build_plan(fw, make_pod().name("probe").req({"cpu": "1"}).obj(), 8)
    # the node axis must physically span all 8 devices
    assert len(state.alloc_r.sharding.device_set) == 8
    assert len(plan.features.sel_match.sharding.device_set) == 8


def test_sharded_chained_sessions_match_host():
    """Multi-batch chained-carry sessions (the depth-2 pipeline) under the
    mesh produce identical assignments to the host oracle."""
    def build(cls):
        cs = FakeClientset()
        kw = {"max_batch": 32} if cls is TPUScheduler else {"deterministic_ties": True}
        s = cls(clientset=cs, **kw)
        for i in range(60):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                           .zone(f"z{i % 5}").obj())
        for i in range(90):  # 3 chained batches of 32
            cs.create_pod(make_pod().name(f"p{i}").req({"cpu": "250m"})
                          .label("app", "s")
                          .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "s"}).obj())
        s.run_until_idle()
        return {p.name: p.node_name for p in cs.pods.values()}, s
    host_asg, _ = build(Scheduler)
    dev_asg, dev = build(TPUScheduler)
    assert dev.mesh is not None and dev.device_batches >= 3
    assert host_asg == dev_asg


def test_two_cells_schedule_independently():
    """The "cells" mesh axis (parallel/mesh.py sharded_schedule_batch):
    n_cells=2 vmaps the kernel over two INDEPENDENT scheduling cells
    (separate clusters scheduled data-parallel, 4-way node sharding each);
    every cell's assignments equal its own single-device run."""
    import jax.numpy as jnp

    from kubernetes_tpu.ops.kernel import schedule_batch
    from kubernetes_tpu.parallel import make_mesh
    from kubernetes_tpu.parallel.mesh import sharded_schedule_batch

    def cell_inputs(seed: int):
        cs = FakeClientset()
        s = TPUScheduler(clientset=cs, mesh=None)
        for i in range(32):
            cs.create_node(make_node().name(f"c{seed}-n{i}")
                           .capacity({"cpu": 8 + (i + seed) % 4,
                                      "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 4}").obj())
        pod = (make_pod().name(f"c{seed}-p").req({"cpu": "500m"})
               .labels({"app": f"cell{seed}"}).obj())
        fw = s.framework_for_pod(pod)
        state, plan = s.build_plan(fw, pod, 8)
        return state, plan

    s0, p0 = cell_inputs(0)
    s1, p1 = cell_inputs(1)
    assert p0.batch_pad == p1.batch_pad and p0.vmax == p1.vmax

    # single-device truth per cell
    r0, _ = schedule_batch(s0, p0.features, p0.batch_pad, p0.fit_strategy,
                           p0.vmax, n_active=np.int32(8))
    r1, _ = schedule_batch(s1, p1.features, p1.batch_pad, p1.fit_strategy,
                           p1.vmax, n_active=np.int32(8))

    mesh = make_mesh(n_cells=2)
    assert dict(mesh.shape) == {"cells": 2, "nodes": 4}
    stack = lambda a, b: jax.tree_util.tree_map(  # noqa: E731
        lambda x, y: jnp.stack([x, y]), a, b)
    run = sharded_schedule_batch(mesh, p0.batch_pad, p0.fit_strategy, p0.vmax)
    out, _carry = run(stack(s0, s1), stack(p0.features, p1.features))
    out = np.asarray(out)
    assert (out[0] == np.asarray(r0)).all()
    assert (out[1] == np.asarray(r1)).all()


def test_multihost_mesh_matches_single_device():
    """(dcn, ici) mesh: the node axis spans hosts; assignments must equal
    the single-device run and the compiled step must contain collectives
    classified per axis (round-4 VERDICT item 7)."""
    import jax
    from kubernetes_tpu.core import FakeClientset
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.parallel import collective_report, make_multihost_mesh
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("needs 4 virtual devices")
    devs = jax.devices()[:4]
    mesh = make_multihost_mesh(2, devices=devs)

    def run(mesh_arg):
        cs = FakeClientset()
        s = TPUScheduler(clientset=cs, mesh=mesh_arg, max_batch=32)
        for i in range(32):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi",
                                      "pods": 110})
                           .zone(f"z{i % 4}").obj())
        proto = make_pod().name("proto").req(
            {"cpu": "250m", "memory": "128Mi"}).labels({"a": "b"}).obj()
        for i in range(64):
            cs.create_pod(proto.clone_from_template(f"p{i}"))
        s.run_until_idle()
        return {p.name: p.node_name for p in cs.pods.values()}, s

    single, _s1 = run(None)
    multi, s2 = run(mesh)
    assert single == multi
    assert s2.scheduled == 64

    from kubernetes_tpu.ops.kernel import schedule_batch
    fw = next(iter(s2.profiles.values()))
    state, plan = s2.build_plan(
        fw, make_pod().name("probe").req({"cpu": "250m"}).obj(), 32)
    lowered = schedule_batch.lower(
        state, plan.features, plan.batch_pad, plan.fit_strategy, plan.vmax,
        n_active=32, carry_in=None, has_pns=plan.has_pns,
        has_ipa_base=plan.has_ipa_base, anti_rowlocal=plan.anti_rowlocal,
        has_na_pref=plan.has_na_pref, port_selfblock=plan.port_selfblock,
        has_aux=plan.has_aux)
    report = collective_report(lowered.compile().as_text(), 2, 2)
    assert report["total"], "no collectives in the multi-host step"


def test_shard_map_is_production_dispatch_for_row_local_plans():
    """Row-local plans at production batch tiers (>64) dispatch through the
    EXPLICIT shard_map lap kernel (parallel/mesh.py sharded_lap_schedule) —
    hand-placed minimal collectives instead of GSPMD inference — and the
    chained multi-batch session stays bit-identical to the host oracle."""
    def build(cls):
        cs = FakeClientset()
        kw = ({"max_batch": 128} if cls is TPUScheduler
              else {"deterministic_ties": True})
        s = cls(clientset=cs, **kw)
        for i in range(96):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 16, "memory": "64Gi",
                                      "pods": 110})
                           .zone(f"z{i % 5}").obj())
        proto = (make_pod().name("proto")
                 .req({"cpu": "250m", "memory": "128Mi"})
                 .labels({"app": "rl"}).obj())
        for i in range(300):  # 3 chained dispatches of 128
            cs.create_pod(proto.clone_from_template(f"p{i}"))
        s.run_until_idle()
        return {p.name: p.node_name for p in cs.pods.values()}, s
    host_asg, _ = build(Scheduler)
    dev_asg, dev = build(TPUScheduler)
    assert dev.mesh is not None
    assert dev.shard_map_dispatches >= 3, (
        "row-local plan did not ride the shard_map lap kernel")
    assert host_asg == dev_asg
    assert dev.host_path_pods == 0


def test_shard_map_collectives_at_or_below_gspmd_baseline():
    """The collective budget (MULTICHIP acceptance): per step, the
    explicit shard_map path must not exceed the GSPMD-compiled baseline in
    any op class total, and should drive the overall count DOWN."""
    import numpy as np
    from kubernetes_tpu.ops.kernel import schedule_batch
    from kubernetes_tpu.parallel.mesh import (collective_report,
                                              mesh_host_split)

    cs = FakeClientset()
    s = TPUScheduler(clientset=cs, max_batch=128)
    for i in range(96):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                       .zone(f"z{i % 4}").obj())
    probe = make_pod().name("probe").req({"cpu": "250m"}).obj()
    rep = s.collective_counts(probe)
    assert rep is not None and rep["path"] == "shard_map", rep
    assert rep["total"], "shard_map step compiled with no collectives"
    # GSPMD baseline of the SAME plan
    fw = s.framework_for_pod(probe)
    state, plan = s.build_plan(fw, probe, 128)
    lowered = schedule_batch.lower(
        state, plan.features, plan.batch_pad, plan.fit_strategy, plan.vmax,
        n_active=np.int32(128), carry_in=None, has_pns=plan.has_pns,
        has_ipa_base=plan.has_ipa_base, anti_rowlocal=plan.anti_rowlocal,
        has_na_pref=plan.has_na_pref, port_selfblock=plan.port_selfblock,
        has_aux=plan.has_aux)
    n_hosts, per_host = mesh_host_split(s.mesh)
    base = collective_report(lowered.compile().as_text(), n_hosts, per_host)
    assert sum(rep["total"].values()) <= sum(base["total"].values()), (
        rep["total"], base["total"])


def test_sidecar_over_uds_matches_in_process():
    """The UDS sidecar prototype (docs/SIDECAR.md): a separate OS process
    owns the device path; scheduling a batch over the socket produces the
    in-process scheduler's assignments."""
    import os
    import re
    import subprocess
    import sys
    import tempfile
    import time

    from kubernetes_tpu.core import FakeClientset, Scheduler
    from kubernetes_tpu.parallel.sidecar import SidecarClient
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    def nodes():
        return [make_node().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
                .zone(f"z{i % 2}").obj() for i in range(6)]

    def pods():
        proto = make_pod().name("proto").req(
            {"cpu": "500m", "memory": "256Mi"}).labels({"a": "b"}).obj()
        return [proto.clone_from_template(f"p{i}") for i in range(20)]

    # in-process oracle
    cs = FakeClientset()
    host = Scheduler(clientset=cs, deterministic_ties=True)
    for n in nodes():
        cs.create_node(n)
    oracle_pods = pods()
    for p in oracle_pods:
        cs.create_pod(p)
    host.run_until_idle()
    oracle = [cs.bindings.get(p.uid) for p in oracle_pods]

    sock_path = os.path.join(tempfile.mkdtemp(), "sidecar.sock")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.parallel.sidecar",
         "--socket", sock_path, "--platform", "cpu"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 120
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if re.search("serving on", line):
                break
        client = SidecarClient(sock_path)
        assert client.ping()
        client.sync_nodes(nodes())
        # two batches: the second sees the first's load (mirror continuity)
        batch = pods()
        got = client.schedule(batch[:10]) + client.schedule(batch[10:])
        assert got == oracle, list(zip(got, oracle))
        client.shutdown_server()
        client.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
