"""Self-healing workload plane (kubernetes_tpu/controllers/workload.py +
the workload API kinds): ReplicaSet/Deployment reconcile, rolling updates
under maxSurge/maxUnavailable, gang lifecycle, PDB-guarded voluntary
disruption, the cluster autoscaler, trace-profile determinism, and HA
leader election (docs/RESILIENCE.md § workload controllers)."""

import time
from urllib.error import HTTPError

import pytest

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.controllers import (ClusterAutoscaler,
                                        WorkloadControllerManager,
                                        WorkloadProfile, gang_member_name,
                                        replica_name)
from kubernetes_tpu.controllers.evictor import RateLimitedEvictor
from kubernetes_tpu.controllers.workload import (DEPLOY_LABEL, OWNER_LABEL,
                                                 _create_pod)
from kubernetes_tpu.core import FakeClientset
from kubernetes_tpu.core.apiserver import (WORKLOAD_KINDS, APIServer,
                                           HTTPClientset)
from kubernetes_tpu.testing.wrappers import make_node


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def plane(tmp_path):
    """Durable apiserver + a workload-kind-reflecting clientset."""
    api = APIServer(data_dir=str(tmp_path / "wal"))
    port = api.serve(0)
    cs = HTTPClientset(f"http://127.0.0.1:{port}",
                       extra_kinds=WORKLOAD_KINDS)
    try:
        yield api, cs
    finally:
        cs.close()
        api.shutdown()


def _add_node(cs, name="n1", cpu=64):
    cs.create_node(make_node().name(name)
                   .capacity({"cpu": cpu, "memory": "256Gi", "pods": 500})
                   .obj())


def _bind_all(cs, node="n1"):
    for p in list(cs.pods.values()):
        if not p.node_name and p.deletion_ts is None:
            try:
                cs.bind(p, node)
            except Exception:  # noqa: BLE001 - already bound / deleted
                pass


# ---------------------------------------------------------------------------
# workload API kinds (replicasets/deployments/pdbs over the real wire)
# ---------------------------------------------------------------------------


class TestWorkloadKinds:
    def test_create_409_put_delete_and_reflection(self, plane):
        _api, cs = plane
        rs = {"name": "web", "replicas": 3, "labels": {"app": "web"}}
        got = cs.create_workload("replicasets", rs)
        assert got["uid"] == "replicasets/default/web"
        with pytest.raises(HTTPError) as ei:
            cs.create_workload("replicasets", rs)
        assert ei.value.code == 409
        cs.put_workload("replicasets", dict(rs, replicas=5))
        _wait(lambda: (cs.workloads["replicasets"].get("default/web") or {})
              .get("replicas") == 5, msg="reflector convergence")
        cs.delete_workload("replicasets", "default", "web")
        _wait(lambda: "default/web" not in cs.workloads["replicasets"],
              msg="delete reflected")

    def test_workloads_survive_recovery(self, plane, tmp_path):
        api, cs = plane
        cs.put_workload("deployments", {"name": "d1", "replicas": 2})
        cs.put_workload("pdbs", {"name": "b1", "minAvailable": 1,
                                 "matchLabels": {"app": "x"}})
        time.sleep(0.1)
        cs.close()
        api.shutdown()
        api2 = APIServer(data_dir=str(tmp_path / "wal"))
        try:
            assert api2.workloads["deployments"]["default/d1"][
                "replicas"] == 2
            assert api2.workloads["pdbs"]["default/b1"][
                "minAvailable"] == 1
        finally:
            api2.shutdown()

    def test_workload_event_handler_fires(self, plane):
        _api, cs = plane
        seen = []
        cs.on_workload_event("pdbs",
                             lambda act, old, w: seen.append((act,
                                                              w["name"])))
        cs.create_workload("pdbs", {"name": "b2", "minAvailable": 1,
                                    "matchLabels": {"app": "y"}})
        _wait(lambda: ("add", "b2") in seen, msg="workload fanout")


# ---------------------------------------------------------------------------
# PDB precondition (eviction subresource + voluntary delete)
# ---------------------------------------------------------------------------


class TestPDBPrecondition:
    def _seed(self, cs, n=3, bound=True):
        for i in range(n):
            p = Pod(name=f"w{i}", uid=f"w{i}", labels={"app": "web"})
            cs.create_pod(p)
            if bound:
                cs.bind(p, "n1")

    def test_eviction_denied_at_min_available(self, plane):
        api, cs = plane
        _add_node(cs)
        self._seed(cs)
        cs.create_workload("pdbs", {"name": "web-pdb", "minAvailable": 3,
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w0", "n1", "i-1")
        assert ei.value.code == 429
        with pytest.raises(HTTPError) as ei:
            cs.delete_pod_voluntary("w1")
        assert ei.value.code == 429
        # involuntary disruption (node death / chaos) is never budgeted
        cs.delete_pod(cs.pods["w2"])
        m = api.expose_metrics()
        assert "apiserver_pod_evictions_budget_denied_total 2" in m

    def test_eviction_allowed_above_floor(self, plane):
        _api, cs = plane
        _add_node(cs)
        self._seed(cs)
        cs.create_workload("pdbs", {"name": "web-pdb", "minAvailable": 2,
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        got = cs.evict_pod("w0", "n1", "i-1")
        assert got.get("evicted") is True
        # the next one would cross the floor (2 bound remain, -1 < 2)
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w1", "n1", "i-2")
        assert ei.value.code == 429

    def test_empty_selector_matches_nothing(self, plane):
        _api, cs = plane
        _add_node(cs)
        self._seed(cs)
        cs.create_workload("pdbs", {"name": "null-pdb", "minAvailable": 9,
                                    "matchLabels": {}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True

    def test_evictor_requeues_budget_blocked(self, plane):
        """The PR 16 evictor treats 429 as retry-later, not terminal:
        the pod re-queues into its ORIGINAL zone and the counter rises."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs)
        cs.create_workload("pdbs", {"name": "web-pdb", "minAvailable": 3,
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        ev = RateLimitedEvictor(cs, primary_qps=100.0, burst=10.0)
        ev.enqueue("z0", "n1", "w0")
        assert ev.run_once() == 0
        assert ev.evictions_budget_blocked == 1
        assert ev.pending_count() == 1  # requeued, not dropped
        # free the budget: the SAME queued intent now commits
        cs.delete_workload("pdbs", "default", "web-pdb")
        time.sleep(0.1)
        assert ev.run_once() == 1
        assert ev.evictions_total == 1

    # -- budget arithmetic: maxUnavailable + percentage forms (ISSUE 19) --

    def test_max_unavailable_int_budget(self, plane):
        """maxUnavailable=1 over 5 bound: exactly one eviction commits;
        the denial payload names the resolved ceiling and the census."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs, n=5)
        cs.create_workload("pdbs", {"name": "web-pdb", "maxUnavailable": 1,
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w1", "n1", "i-2")
        assert ei.value.code == 429
        # an evicted pod is UNBOUND, not deleted (it re-queues for
        # rescheduling), so the matched census still counts it
        body = ei.value.read().decode()
        assert '"maxUnavailable":1' in body and '"matched":5' in body

    def test_min_available_percentage_rounds_up(self, plane):
        """minAvailable='60%' over 5 matched resolves to ceil(3.0)=3:
        exactly two evictions commit (4>=3, 3>=3) and the third would dip
        the bound count to 2 < 3."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs, n=5)
        cs.create_workload("pdbs", {"name": "web-pdb",
                                    "minAvailable": "60%",
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True
        assert cs.evict_pod("w1", "n1", "i-2").get("evicted") is True
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w2", "n1", "i-3")
        assert ei.value.code == 429
        assert '"minAvailable":3' in ei.value.read().decode()

    def test_max_unavailable_percentage_rounds_down(self, plane):
        """maxUnavailable='30%' over 8 matched resolves to floor(2.4)=2 —
        the conservative direction (never disrupt MORE than the share):
        two evictions commit, the third answers 429."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs, n=8)
        cs.create_workload("pdbs", {"name": "web-pdb",
                                    "maxUnavailable": "30%",
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True
        assert cs.evict_pod("w1", "n1", "i-2").get("evicted") is True
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w2", "n1", "i-3")
        assert ei.value.code == 429

    def test_percentage_base_counts_unbound_matched_pods(self, plane):
        """The percent base is the full matched census (the workload's
        size), not just bound pods: 4 bound + 2 pending matched pods with
        minAvailable='50%' resolve the floor to ceil(3.0)=3 over 6 — one
        eviction commits (3>=3), the second dips to 2 and is denied. The
        evicted pod stays in the census (unbound, requeued), so the base
        holds at 6 throughout."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs, n=4)
        for i in range(2):
            cs.create_pod(Pod(name=f"pend{i}", uid=f"pend{i}",
                              labels={"app": "web"}))
        cs.create_workload("pdbs", {"name": "web-pdb",
                                    "minAvailable": "50%",
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w1", "n1", "i-2")
        assert ei.value.code == 429
        body = ei.value.read().decode()
        assert '"matched":6' in body and '"minAvailable":3' in body

    def test_both_budget_forms_must_pass(self, plane):
        """minAvailable AND maxUnavailable on one PDB: the stricter form
        gates. 6 bound, minAvailable=1, maxUnavailable=2: the third
        eviction passes the minAvailable floor (3>=1) but breaches
        maxUnavailable (3 < 6-2) and is denied — and the voluntary-delete
        path enforces the same arithmetic."""
        _api, cs = plane
        _add_node(cs)
        self._seed(cs, n=6)
        cs.create_workload("pdbs", {"name": "web-pdb", "minAvailable": 1,
                                    "maxUnavailable": 2,
                                    "matchLabels": {"app": "web"}})
        time.sleep(0.1)
        assert cs.evict_pod("w0", "n1", "i-1").get("evicted") is True
        assert cs.evict_pod("w1", "n1", "i-2").get("evicted") is True
        with pytest.raises(HTTPError) as ei:
            cs.evict_pod("w2", "n1", "i-3")
        assert ei.value.code == 429
        with pytest.raises(HTTPError) as ei:
            cs.delete_pod_voluntary("w2")
        assert ei.value.code == 429


# ---------------------------------------------------------------------------
# ReplicaSet / Deployment reconcile (single ACTIVE manager, in-process)
# ---------------------------------------------------------------------------


def _manager(cs, ident="m0", **kw):
    return WorkloadControllerManager(cs, ident, lease_ttl=1.0, tick=0.03,
                                     **kw)


class TestReplicaSetReconcile:
    def test_creates_deterministic_replicas_and_self_heals(self, plane):
        _api, cs = plane
        _add_node(cs)
        m = _manager(cs)
        cs.put_workload("replicasets", {
            "name": "web", "replicas": 3, "revision": 0,
            "template": {"labels": {"app": "web"}, "cpuMilli": 100}})
        m.start()
        try:
            want = {replica_name("web", 0, i) for i in range(3)}
            _wait(lambda: set(cs.pods) >= want, msg="replicas created")
            # chaos-kill one replica: the SAME name must come back
            victim = sorted(want)[0]
            created_before = m.replicasets.pods_created
            cs.delete_pod(cs.pods[victim])
            _wait(lambda: m.replicasets.pods_created > created_before,
                  msg="self-heal create")
            _wait(lambda: victim in cs.pods, msg="victim recreated")
            live = [p.name for p in cs.pods.values()
                    if p.deletion_ts is None]
            assert sorted(live) == sorted(set(live))  # zero duplicates
        finally:
            m.stop()

    def test_scale_down_is_voluntary_and_pdb_guarded(self, plane):
        _api, cs = plane
        _add_node(cs)
        m = _manager(cs)
        cs.put_workload("replicasets", {
            "name": "web", "replicas": 3, "revision": 0,
            "template": {"labels": {"app": "web"}, "cpuMilli": 100}})
        cs.create_workload("pdbs", {"name": "web-pdb", "minAvailable": 3,
                                    "matchLabels": {"app": "web"}})
        m.start()
        try:
            _wait(lambda: sum(1 for p in cs.pods.values()
                              if p.labels.get(OWNER_LABEL) == "web") == 3,
                  msg="replicas created")
            _bind_all(cs)
            cs.put_workload("replicasets", {
                "name": "web", "replicas": 2, "revision": 0,
                "template": {"labels": {"app": "web"}, "cpuMilli": 100}})
            # the PDB floor (3) blocks the scale-down delete: blocked
            # counter rises, all 3 stay live
            _wait(lambda: m.replicasets.deletes_blocked > 0,
                  msg="delete blocked by PDB")
            assert sum(1 for p in cs.pods.values()
                       if p.labels.get(OWNER_LABEL) == "web"
                       and p.deletion_ts is None) == 3
            # lower the floor: the drain goes through
            cs.put_workload("pdbs", {"name": "web-pdb", "minAvailable": 1,
                                     "matchLabels": {"app": "web"}})
            _wait(lambda: sum(1 for p in cs.pods.values()
                              if p.labels.get(OWNER_LABEL) == "web"
                              and p.deletion_ts is None) == 2,
                  msg="scale-down drained")
        finally:
            m.stop()


class TestRollingUpdate:
    def test_rollout_respects_surge_and_floor(self, plane):
        _api, cs = plane
        _add_node(cs)
        m = _manager(cs)
        dep = {"name": "api", "replicas": 3, "revision": 0,
               "maxSurge": 1, "maxUnavailable": 1,
               "template": {"labels": {"app": "api"}, "cpuMilli": 100}}
        cs.put_workload("deployments", dep)
        # The HARD availability floor is the server-side PDB precondition
        # (the controller's own budget pacing reads a reflector cache
        # that can lag one event behind): a wave never takes the
        # workload below minAvailable = replicas - maxUnavailable.
        cs.create_workload("pdbs", {"name": "api-pdb", "minAvailable": 2,
                                    "matchLabels": {"app": "api"}})
        m.start()
        try:
            _wait(lambda: sum(1 for p in cs.pods.values()
                              if p.labels.get(DEPLOY_LABEL) == "api") == 3,
                  msg="initial rollout")
            _bind_all(cs)
            _wait(lambda: m.deployments.rollouts_completed >= 1,
                  msg="revision 0 complete")
            cs.put_workload("deployments", dict(dep, revision=1))
            ceiling = dep["replicas"] + dep["maxSurge"]
            floor = dep["replicas"] - dep["maxUnavailable"]
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                pods = [p for p in cs.pods.values()
                        if p.labels.get(DEPLOY_LABEL) == "api"
                        and p.deletion_ts is None]
                assert len(pods) <= ceiling, \
                    f"surge ceiling broken: {len(pods)} > {ceiling}"
                bound = sum(1 for p in pods if p.node_name)
                assert bound >= floor, \
                    f"availability floor broken: {bound} < {floor}"
                _bind_all(cs)
                if (len(pods) == 3
                        and all(p.labels[OWNER_LABEL] == "api-1"
                                for p in pods)
                        and "default/api-0"
                        not in cs.workloads["replicasets"]):
                    break
                time.sleep(0.02)
            else:
                raise AssertionError("rollout never completed")
            want = {replica_name("api-1", 1, i) for i in range(3)}
            assert {p.name for p in cs.pods.values()
                    if p.labels.get(DEPLOY_LABEL) == "api"} == want
            assert m.deployments.rs_deleted >= 1  # old RS GC'd
        finally:
            m.stop()


# ---------------------------------------------------------------------------
# gang controller (PodGroups + members, whole-gang restart)
# ---------------------------------------------------------------------------


class TestGangController:
    def test_launch_then_whole_restart_on_member_loss(self, plane):
        _api, cs = plane
        _add_node(cs)
        m = _manager(cs)
        m.gangs.set_gang({"name": "train", "size": 3, "cpuMilli": 50})
        m.start()
        try:
            want0 = {gang_member_name("train", 0, i) for i in range(3)}
            _wait(lambda: set(cs.pods) >= want0, msg="gang launched")
            assert "default/train" in cs.pod_groups  # minted over HTTP
            # only restart once the controller OBSERVED completeness —
            # otherwise the loss is launch-lag and heals by catch-up
            _wait(lambda: m.gangs._completed.get("train", -1) >= 0,
                  msg="observed complete")
            cs.delete_pod(cs.pods[gang_member_name("train", 0, 1)])
            want1 = {gang_member_name("train", 1, i) for i in range(3)}
            _wait(lambda: want1 <= {p.name for p in cs.pods.values()},
                  msg="whole-gang restart at r1")
            assert m.gangs.restarts == 1
            # r0 stragglers drain; exactly one live cohort at quiesce
            _wait(lambda: not any(
                p.name in want0 for p in cs.pods.values()
                if p.deletion_ts is None), msg="r0 drained")
        finally:
            m.stop()

    def test_catchup_heals_launch_loss_without_restart(self, plane):
        _api, cs = plane
        _add_node(cs)
        m = _manager(cs)
        m.gangs.set_gang({"name": "fresh", "size": 2, "cpuMilli": 50})
        # First reconcile mints r0; a takeover (fresh controller, empty
        # _completed) with a missing member must catch up, not restart.
        m.tick_once()
        _wait(lambda: len([p for p in cs.pods.values()
                           if p.pod_group == "fresh"]) == 2,
              msg="gang minted")
        cs.delete_pod(cs.pods[gang_member_name("fresh", 0, 0)])
        _wait(lambda: gang_member_name("fresh", 0, 0) not in cs.pods,
              msg="member gone")
        m2 = _manager(cs, "m-takeover")
        m2.gangs.set_gang({"name": "fresh", "size": 2, "cpuMilli": 50})
        # m2 first has to WIN the lease (m0's grant outlives it by up to
        # one TTL); its first ACTIVE tick then catches up — m0 absent but
        # never seen complete means launch-lag, not member death.
        _wait(lambda: (m2.tick_once(), m2.active)[1], msg="m2 takeover")
        _wait(lambda: gang_member_name("fresh", 0, 0) in cs.pods,
              msg="catch-up create")
        assert m2.gangs.restarts == 0
        assert m2.gangs.pods_created + m2.gangs.creates_409 >= 1


# ---------------------------------------------------------------------------
# cluster autoscaler (injected clock, FakeClientset — no sleeps)
# ---------------------------------------------------------------------------


class TestClusterAutoscaler:
    def _pending(self, cs, n):
        for i in range(n):
            cs.create_pod(Pod(name=f"q{i}", uid=f"q{i}"))

    def test_scales_up_on_backlog_age_with_cooldown(self):
        cs = FakeClientset()
        clock = [0.0]
        a = ClusterAutoscaler(cs, max_nodes=3, wave=2, pending_age_s=2.0,
                              cooldown_s=5.0, now=lambda: clock[0])
        self._pending(cs, 4)
        a.reconcile_once()
        assert a.nodes_added == 0  # backlog too young
        clock[0] = 2.5
        a.reconcile_once()
        assert a.nodes_added == 2 and len(cs.nodes) == 2
        clock[0] = 3.0
        a.reconcile_once()
        assert a.nodes_added == 2  # cooldown holds the second wave
        clock[0] = 8.0
        a.reconcile_once()
        assert a.nodes_added == 3 and len(cs.nodes) == 3  # max bound

    def test_scales_down_own_empty_nodes_only(self):
        cs = FakeClientset()
        clock = [0.0]
        a = ClusterAutoscaler(cs, min_nodes=1, wave=2, pending_age_s=1.0,
                              cooldown_s=0.0, now=lambda: clock[0])
        cs.create_node(make_node().name("static-0")
                       .capacity({"cpu": 8, "memory": "32Gi",
                                  "pods": 110}).obj())
        self._pending(cs, 2)
        a.reconcile_once()  # seeds the backlog ages at first sight
        clock[0] = 2.0
        a.reconcile_once()
        assert a.nodes_added == 2
        # occupy one autoscaled node; drain the backlog
        cs.bind(cs.pods["q0"], "autoscale-0")
        cs.delete_pod(cs.pods["q1"])
        clock[0] = 4.0
        a.reconcile_once()
        # occupied autoscale-0 and foreign static-0 survive
        assert set(cs.nodes) == {"static-0", "autoscale-0"}
        assert a.nodes_removed == 1

    def test_reaged_backlog_after_takeover_gets_grace(self):
        """A fresh controller re-ages the backlog from ITS first sight:
        one full pending_age_s of grace after failover, no scale storm."""
        cs = FakeClientset()
        clock = [100.0]
        self._pending(cs, 1)
        a = ClusterAutoscaler(cs, pending_age_s=2.0, cooldown_s=0.0,
                              now=lambda: clock[0])
        a.reconcile_once()
        assert a.nodes_added == 0  # aged from first sight, not pod birth
        clock[0] = 102.5
        a.reconcile_once()
        assert a.nodes_added > 0


# ---------------------------------------------------------------------------
# trace-profile marginals
# ---------------------------------------------------------------------------


class TestWorkloadProfile:
    def test_specs_deterministic_and_sorted(self):
        a = WorkloadProfile(deployments=6, gangs=3, seed=7).specs()
        b = WorkloadProfile(deployments=6, gangs=3, seed=7).specs()
        assert a == b
        assert [s["arrival"] for s in a] == sorted(s["arrival"] for s in a)
        assert WorkloadProfile(deployments=6, gangs=3, seed=8).specs() != a

    def test_marginals_respect_declared_support(self):
        prof = WorkloadProfile(deployments=20, gangs=10, seed=3,
                               mean_lifetime_s=30.0, min_lifetime_s=5.0)
        specs = prof.specs()
        assert sum(1 for s in specs if s["kind"] == "deployment") == 20
        assert sum(1 for s in specs if s["kind"] == "gang") == 10
        for s in specs:
            assert s["lifetime"] >= 5.0
            assert s["cpuMilli"] in prof.cpu_milli_choices
            if s["kind"] == "deployment":
                assert s["replicas"] in prof.replica_choices
            else:
                assert s["size"] in prof.gang_sizes

    def test_immortal_default(self):
        import math
        for s in WorkloadProfile(deployments=2, gangs=1).specs():
            assert s["lifetime"] == math.inf


# ---------------------------------------------------------------------------
# HA manager (lease CAS, in-process pair) + profile feed/expiry
# ---------------------------------------------------------------------------


class TestManagerHA:
    def test_single_active_and_takeover(self, plane):
        _api, cs = plane
        m1 = _manager(cs, "m1")
        m2 = _manager(cs, "m2")
        m1.start()
        m2.start()
        try:
            _wait(lambda: m1.active or m2.active, msg="one ACTIVE")
            time.sleep(0.2)
            assert not (m1.active and m2.active), "split brain"
            active, standby = (m1, m2) if m1.active else (m2, m1)
            active.stop()
            _wait(lambda: standby.active, timeout=10.0, msg="takeover")
            assert standby.takeovers >= 1
        finally:
            m1.stop()
            m2.stop()

    def test_profile_feed_and_two_phase_expiry(self, plane):
        _api, cs = plane
        _add_node(cs)
        prof = WorkloadProfile(deployments=1, gangs=1, arrival_rate=50.0,
                               mean_lifetime_s=0.9, min_lifetime_s=0.9,
                               seed=5, name_prefix="tp")
        m = _manager(cs, profile=prof)
        m.start()
        try:
            _wait(lambda: m.profile_fed == 2, msg="profile admitted")
            _wait(lambda: m.profile_expired == 2, timeout=30.0,
                  msg="two-phase expiry")
            _wait(lambda: not [p for p in cs.pods.values()
                               if p.deletion_ts is None],
                  msg="all workload pods drained")
            _wait(lambda: not cs.workloads["deployments"],
                  msg="deployments deleted")
            # orphaned-RS cascade GC may trail by a tick (reflector-lag
            # re-PUT right after the deployment delete)
            _wait(lambda: not cs.workloads["replicasets"],
                  msg="replicasets cascaded")
        finally:
            m.stop()


def test_create_seam_treats_409_as_success(plane):
    _api, cs = plane
    p = Pod(name="dup", uid="dup")
    assert _create_pod(cs, p) is True
    assert _create_pod(cs, p) is False  # 409 collapses to not-created
