"""CompositePodGroup hierarchies + workload forest
(backend/queue/workload_forest.go, schedule_one_podgroup.go composite paths,
kube_features.go CompositePodGroup gate): the whole TREE pops as one queue
entity once every leaf group is complete, and schedules all-or-nothing
across levels — any leaf failure rolls the entire tree back."""

from kubernetes_tpu.api.types import CompositePodGroup, PodGroup
from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.config import SchedulerConfiguration
from kubernetes_tpu.models import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _sched(cls=Scheduler, **kw):
    cs = FakeClientset()
    cfg = SchedulerConfiguration(feature_gates={"CompositePodGroup": True})
    if cls is Scheduler:
        kw.setdefault("deterministic_ties", True)
    return cs, cls(clientset=cs, config=cfg, **kw)


def _members(cs, group_name, n, cpu="500m"):
    proto = make_pod().name("proto").req({"cpu": cpu}).obj()
    out = []
    for i in range(n):
        p = proto.clone_from_template(f"{group_name}-m{i}")
        p.pod_group = group_name
        cs.create_pod(p)
        out.append(p)
    return out


def test_tree_waits_for_every_leaf():
    cs, sched = _sched()
    for i in range(10):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    cs.create_pod_group(PodGroup(name="a", min_count=2, parent_name="root"))
    cs.create_pod_group(PodGroup(name="b", min_count=2, parent_name="root"))
    pa = _members(cs, "a", 2)
    sched.run_until_idle()
    # leaf b incomplete: NOTHING schedules, even though a is ready
    assert all(cs.bindings.get(p.uid) is None for p in pa)
    pb = _members(cs, "b", 2)
    sched.run_until_idle()
    assert all(cs.bindings.get(p.uid) for p in pa + pb)


def test_nested_composites_schedule_atomically():
    cs, sched = _sched()
    for i in range(10):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    cs.create_composite_pod_group(CompositePodGroup(name="mid", parent_name="root"))
    cs.create_pod_group(PodGroup(name="x", min_count=1, parent_name="mid"))
    cs.create_pod_group(PodGroup(name="y", min_count=1, parent_name="root"))
    px = _members(cs, "x", 1)
    py = _members(cs, "y", 1)
    sched.run_until_idle()
    assert all(cs.bindings.get(p.uid) for p in px + py)


def test_leaf_failure_rolls_back_whole_tree():
    cs, sched = _sched()
    for i in range(3):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "4", "pods": 110}).obj())
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    cs.create_pod_group(PodGroup(name="ok", min_count=2, parent_name="root"))
    cs.create_pod_group(PodGroup(name="big", min_count=1, parent_name="root"))
    p_ok = _members(cs, "ok", 2, cpu="1")
    p_big = _members(cs, "big", 1, cpu="64")  # fits nowhere
    sched.run_until_idle()
    # the feasible leaf must NOT have committed (all-or-nothing across levels)
    assert all(cs.bindings.get(p.uid) is None for p in p_ok + p_big)
    assert sched.failures >= 1
    # freeing capacity lets the whole tree schedule
    cs.create_node(make_node().name("huge")
                   .capacity({"cpu": "128", "pods": 110}).obj())
    import time
    deadline = time.monotonic() + 15
    while (time.monotonic() < deadline
           and any(cs.bindings.get(p.uid) is None for p in p_ok + p_big)):
        sched.queue.flush_backoff_completed()
        sched.run_until_idle()
        time.sleep(0.1)
    assert all(cs.bindings.get(p.uid) for p in p_ok + p_big)


def test_late_parent_completes_the_tree():
    """Child→parent links are recorded before the parent is observed; the
    tree activates when the late parent arrives (workload_forest.go
    invariant)."""
    cs, sched = _sched()
    for i in range(6):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_pod_group(PodGroup(name="a", min_count=1, parent_name="root"))
    pa = _members(cs, "a", 1)
    sched.run_until_idle()
    assert cs.bindings.get(pa[0].uid) is None  # root unobserved: tree waits
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    sched.run_until_idle()
    assert cs.bindings.get(pa[0].uid)


def test_composite_gate_off_schedules_flat():
    """With the CompositePodGroup gate off, parent links are ignored and
    groups schedule as flat gangs (kube_features.go:158 gating)."""
    cs = FakeClientset()
    sched = Scheduler(clientset=cs, deterministic_ties=True)
    for i in range(4):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_pod_group(PodGroup(name="a", min_count=1, parent_name="root"))
    pa = _members(cs, "a", 1)
    sched.run_until_idle()
    assert cs.bindings.get(pa[0].uid)


def test_composite_on_tpu_scheduler():
    cs, sched = _sched(TPUScheduler)
    for i in range(8):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    cs.create_pod_group(PodGroup(name="a", min_count=2, parent_name="root"))
    cs.create_pod_group(PodGroup(name="b", min_count=2, parent_name="root"))
    pa = _members(cs, "a", 2)
    pb = _members(cs, "b", 2)
    sched.run_until_idle()
    assert all(cs.bindings.get(p.uid) for p in pa + pb)


def test_deleted_member_is_not_scheduled_and_tree_recovers():
    """A member deleted while its composite tree is queued must not be
    committed; the tree re-activates from the filtered buffers."""
    cs, sched = _sched()
    for i in range(6):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cs.create_composite_pod_group(CompositePodGroup(name="root"))
    cs.create_pod_group(PodGroup(name="a", min_count=2, parent_name="root"))
    pa = _members(cs, "a", 3)  # one extra member
    cs.delete_pod(pa[0])
    sched.run_until_idle()
    assert cs.bindings.get(pa[0].uid) is None
    assert all(cs.bindings.get(p.uid) for p in pa[1:])


def test_empty_tree_is_dropped_not_parked():
    """An all-leaves-memberless composite tree must be DROPPED, not parked
    unschedulable: an empty unschedulable_plugins set makes every cluster
    event relevant, producing a busy reactivate/re-park loop until members
    arrive (round-4 advisor finding). The member buffers re-activate the
    tree when members show up."""
    from kubernetes_tpu.core.queue import QueuedCompositeGroupInfo

    cs, sched = _sched()
    for i in range(4):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110}).obj())
    cpg = CompositePodGroup(name="root")
    cs.create_composite_pod_group(cpg)
    ga = PodGroup(name="a", min_count=2, parent_name="root")
    cs.create_pod_group(ga)
    sched.run_until_idle()

    qcgi = QueuedCompositeGroupInfo(cpg=cpg, groups=[(ga, [])])
    sched.queue._in_flight[qcgi.uid] = 0
    sched.schedule_composite_group(qcgi)
    # not parked: no unschedulable entity, no in-flight leak
    assert sched.queue.unschedulable.get(qcgi.uid) is None
    assert qcgi.uid not in sched.queue._in_flight
    # members arriving later still schedule the tree through the buffers
    _members(cs, "a", 2)
    sched.run_until_idle()
    assert sum(1 for u in cs.bindings if cs.bindings[u]) >= 2
