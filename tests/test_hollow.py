"""Hollow-node scale plane (kubernetes_tpu/hollow/, docs/SCALE.md).

Covers: profile roundtrip + deterministic shape mix + node-wire schema;
plane lifecycle against a real apiserver (bulk registration, bulk
heartbeats through the status sink, capacity drift as real node updates,
cordon/delete/re-register churn keeping the fleet size constant); a
scheduler binding pods against a hollow fleet while churn runs
(exactly-once); and the `python -m kubernetes_tpu.hollow` process the
shard/perf harness spawns.
"""

import json
import signal
import subprocess
import sys
import time

import pytest

from kubernetes_tpu.core import Scheduler
from kubernetes_tpu.core.apiserver import (
    APIServer,
    HTTPClientset,
    node_from_wire,
)
from kubernetes_tpu.hollow import HollowNodePlane, HollowProfile, NodeShape
from kubernetes_tpu.testing.wrappers import make_pod


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def api():
    server = APIServer()
    port = server.serve(0)
    try:
        yield server, f"http://127.0.0.1:{port}"
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# profile
# ---------------------------------------------------------------------------


class TestProfile:
    def test_dict_roundtrip(self):
        prof = HollowProfile(
            count=321, zones=7, heartbeat_s=12.0, drift=0.25,
            churn_per_s=1.5,
            shapes=[NodeShape(weight=3),
                    NodeShape(weight=1, cpu=96, memory="1Ti", pods=250,
                              labels={"pool": "big"},
                              taints=[{"key": "big",
                                       "effect": "NoSchedule"}])])
        again = HollowProfile.from_dict(prof.to_dict())
        assert again.to_dict() == prof.to_dict()

    def test_shape_mix_is_weighted_and_deterministic(self):
        prof = HollowProfile(
            count=1000,
            shapes=[NodeShape(weight=3, cpu=32),
                    NodeShape(weight=1, cpu=96)])
        picks = [prof.shape_for(i).cpu for i in range(1000)]
        assert picks == [prof.shape_for(i).cpu for i in range(1000)]
        big = sum(1 for c in picks if c == 96)
        assert 150 < big < 350     # ~1/4 of the fleet
        # single-shape profile: everything is that shape
        assert all(HollowProfile(count=10).shape_for(i).cpu == 32
                   for i in range(10))

    def test_low_weight_shapes_never_quantize_to_zero(self):
        """A 1-in-100 shape must still get ~1% of a big fleet — a fixed
        modular period would round it down to ZERO nodes."""
        prof = HollowProfile(
            count=50000,
            shapes=[NodeShape(weight=99, cpu=32),
                    NodeShape(weight=1, cpu=96)])
        big = sum(1 for i in range(50000) if prof.shape_for(i).cpu == 96)
        assert 300 < big < 700     # ~500 expected

    def test_node_wire_decodes_through_the_server_codec(self):
        prof = HollowProfile(
            count=4, zones=2,
            shapes=[NodeShape(cpu=16, memory="64Gi", pods=55,
                              labels={"pool": "x"},
                              taints=[{"key": "k", "value": "v",
                                       "effect": "NoSchedule"}],
                              scalars={"example.com/foo": 3})])
        node = node_from_wire(prof.node_wire(1))
        assert node.name == "hollow-1" and node.uid == "hollow-1"
        assert node.allocatable.milli_cpu == 16000
        assert node.allocatable.allowed_pod_number == 55
        assert node.allocatable.scalar_resources == {"example.com/foo": 3}
        assert node.labels["pool"] == "x"
        assert node.labels["topology.kubernetes.io/zone"] == "zone-1"
        assert node.labels["kubernetes.io/hostname"] == "hollow-1"
        assert node.taints[0].key == "k"


# ---------------------------------------------------------------------------
# plane lifecycle against a real apiserver
# ---------------------------------------------------------------------------


class TestPlane:
    def test_register_heartbeat_drift_churn(self, api):
        server, base = api
        prof = HollowProfile(
            count=120, zones=6, heartbeat_s=0.8, drift=0.3,
            churn_per_s=8.0, churn_cordon_s=0.05, register_chunk=50,
            shapes=[NodeShape(weight=2),
                    NodeShape(weight=1, cpu=96, labels={"pool": "big"})])
        plane = HollowNodePlane(base, prof)
        assert plane.register() == 120
        assert len(server.store.nodes) == 120
        assert sum(1 for n in server.store.nodes.values()
                   if n.labels.get("pool") == "big") > 20
        plane.start()
        try:
            _wait(lambda: plane.heartbeats >= 240,
                  msg="two full heartbeat sweeps")
            # bulk heartbeats landed on the server's sink, per node
            assert server.node_heartbeats >= 120
            _wait(lambda: plane.drifts >= 5, msg="capacity drift")
            # a drifted node's allocatable really changed in the store
            drifted = [n for n in server.store.nodes.values()
                       if n.allocatable.milli_cpu
                       not in (32000, 96000)]
            assert drifted
            _wait(lambda: plane.deletes >= 3 and plane.reregisters >= 3,
                  msg="churn waves")
            assert plane.cordons >= plane.deletes
        finally:
            plane.stop()
        # fleet size stays constant through churn: every delete was
        # matched by a replacement registration
        assert len(server.store.nodes) == 120
        assert any(n.startswith("hollow-r")
                   for n in server.store.nodes)
        assert plane.errors == 0
        stats = plane.stats()
        assert stats["live"] == 120 and stats["registered"] == 120

    def test_scheduler_binds_against_hollow_fleet_under_churn(self, api):
        """Exactly-once scheduling against an impersonated fleet while
        cordon/delete/re-register waves run — the hollow plane's events
        flow through the same watch plane as real node churn."""
        server, base = api
        prof = HollowProfile(count=40, zones=4, heartbeat_s=1.0,
                             drift=0.1, churn_per_s=4.0,
                             churn_cordon_s=0.05)
        plane = HollowNodePlane(base, prof)
        plane.register()
        plane.start()
        cs = HTTPClientset(base)
        sched = Scheduler(clientset=cs)
        try:
            _wait(lambda: len(cs.nodes) >= 40, msg="fleet in cache")
            pods = [make_pod().name(f"p{i}").req(
                {"cpu": "100m", "memory": "64Mi"}).obj()
                for i in range(30)]
            for p in pods:
                cs.create_pod(p)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                sched.run_until_idle()
                if len(server.store.bindings) >= 30:
                    break
                time.sleep(0.05)
            bound = {u: n for u, n in server.store.bindings.items()}
            assert len(bound) == 30
            assert set(bound) == {p.uid for p in pods}
            # every placement names a node that existed in the fleet
            assert all(n.startswith("hollow") for n in bound.values())
        finally:
            plane.stop()
            cs.close()
            server.shutdown()


# ---------------------------------------------------------------------------
# the spawned process (what the shard/perf harness runs)
# ---------------------------------------------------------------------------


class TestHollowProcess:
    def test_cli_registers_heartbeats_and_reports_stats(self, api, tmp_path):
        server, base = api
        prof_path = tmp_path / "profile.json"
        prof_path.write_text(json.dumps(HollowProfile(
            count=30, zones=3, heartbeat_s=0.5, churn_per_s=2.0,
            churn_cordon_s=0.05).to_dict()))
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.hollow",
             "--api-url", base, "--profile", str(prof_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            line = proc.stdout.readline()
            assert "registered 30 nodes" in line
            assert len(server.store.nodes) == 30
            _wait(lambda: server.node_heartbeats >= 30,
                  msg="heartbeats from the process")
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=15)
            stats = json.loads(
                [ln for ln in out.splitlines()
                 if "hollow_stats" in ln][-1])["hollow_stats"]
            assert stats["registered"] == 30
            assert stats["heartbeats"] >= 30
        finally:
            if proc.poll() is None:
                proc.kill()


# ---------------------------------------------------------------------------
# failure injection: silence / flap / zone outage (PR-16 node lifecycle)
# ---------------------------------------------------------------------------


class TestFailureInjection:
    def test_profile_roundtrip_with_failure_fields(self):
        prof = HollowProfile(
            count=50, zones=5, silence=0.1, silence_after_s=1.5,
            flap=0.05, flap_period_s=3.0, outage_zone=2,
            outage_after_s=4.0)
        again = HollowProfile.from_dict(prof.to_dict())
        assert again.to_dict() == prof.to_dict()
        assert again.silence == 0.1 and again.outage_zone == 2

    def test_silent_victims_are_deterministic_and_churn_exempt(self, api):
        """The silenced set is a pure function of the profile seed (the
        chaos oracle direct-binds victim pods onto it), silence never
        perturbs the drift/churn RNG streams, and churn never cordons a
        silent node — a dead node stays dead instead of being recycled
        into a healthy replacement."""
        server, base = api
        prof = HollowProfile(count=60, zones=6, heartbeat_s=0.3,
                             churn_per_s=4.0, churn_cordon_s=0.05,
                             silence=0.2, silence_after_s=0.2, seed=13)
        plane = HollowNodePlane(base, prof)
        plane.register()
        plane.start()
        try:
            silent = plane.silent_nodes()
            assert len(silent) == 12
            assert plane.stats()["silenced"] == 12
            _wait(lambda: plane.stats()["silenced_beats"] > 0,
                  msg="silence filtering")
            _wait(lambda: plane.deletes >= 3, msg="churn waves")
            # silent nodes survived every churn wave untouched
            assert set(silent) <= set(server.store.nodes)
            # the server's freshness map shows them aging while the rest
            # of the fleet stays young
            time.sleep(0.8)
            ages = server.heartbeat_ages()
            stale = [n for n in silent if ages[n] > 0.6]
            assert len(stale) == len(silent), (len(stale), len(silent))
        finally:
            plane.stop()
        # same profile, fresh plane+server: identical victim set
        server2 = APIServer()
        port2 = server2.serve(0)
        plane2 = HollowNodePlane(f"http://127.0.0.1:{port2}", prof)
        plane2.register()
        plane2.start()
        try:
            assert plane2.silent_nodes() == silent
        finally:
            plane2.stop()
            server2.shutdown()

    def test_flappers_alternate_and_outage_zone_goes_dark(self, api):
        server, base = api
        prof = HollowProfile(count=40, zones=4, heartbeat_s=0.2,
                             flap=0.1, flap_period_s=0.6,
                             outage_zone=1, outage_after_s=0.4, seed=5)
        plane = HollowNodePlane(base, prof)
        plane.register()
        plane.start()
        try:
            assert plane.stats()["flapping"] == 4
            # outage zone: every zone-1 node stops heartbeating after
            # outage_after_s while other zones stay fresh
            time.sleep(1.2)
            ages = server.heartbeat_ages()
            zone_of = {n: node.labels["topology.kubernetes.io/zone"]
                       for n, node in server.store.nodes.items()}
            dark = [n for n, z in zone_of.items() if z == "zone-1"]
            lit = [n for n, z in zone_of.items()
                   if z != "zone-1" and n not in plane._flappers]
            assert all(ages[n] > 0.6 for n in dark)
            assert any(ages[n] < 0.5 for n in lit)
            # flappers come back: within one full period each flapper
            # heartbeats again (age resets) at least once
            flapper = sorted(plane._flappers)[0]
            if zone_of[flapper] == "zone-1":
                flapper = next(n for n in sorted(plane._flappers)
                               if zone_of[n] != "zone-1") \
                    if any(zone_of[n] != "zone-1"
                           for n in plane._flappers) else flapper
            if zone_of[flapper] != "zone-1":
                def _beats_again():
                    return server.heartbeat_ages()[flapper] < 0.3
                _wait(_beats_again, timeout=3.0, msg="flapper alive phase")
        finally:
            plane.stop()
