"""Node-lifecycle chaos acceptance (ISSUE PR-16, docs/RESILIENCE.md § node
lifecycle): (a) a 5k-node hollow MixedChurn fleet with 10% of nodes going
permanently silent — every pod on a silenced node is evicted and
rescheduled exactly once while the surviving fleet's placements stay
untouched; (b) a full zone outage engages the FullDisruption throttle
(zero evictions in the dead zone) while isolated failures elsewhere still
drain; (c) ``kill -9`` of the apiserver LEADER mid-eviction-wave — the
wave resumes against the promoted follower with zero double-evictions
(deterministic intents + the WAL-replicated ledger)."""

import json
import threading
import time
from urllib import request as urlrequest
from urllib.error import HTTPError

import pytest

from kubernetes_tpu.controllers import NodeLifecycleController
from kubernetes_tpu.controllers.evictor import ZONE_FULL, ZONE_NORMAL, intent_for
from kubernetes_tpu.core import Scheduler
from kubernetes_tpu.core.apiserver import (EVICTED_ANNOTATION,
                                           UNREACHABLE_TAINT, APIServer,
                                           HTTPClientset, pod_to_wire)
from kubernetes_tpu.core.backoff import RetryConfig
from kubernetes_tpu.core.clientset import RetryingClientset
from kubernetes_tpu.hollow import HollowNodePlane, HollowProfile
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE_LABEL = "topology.kubernetes.io/zone"


def _call(base, method, path, body=None, timeout=30.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urlrequest.Request(base + path, data=data, method=method,
                            headers={"Content-Type": "application/json"})
    with urlrequest.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def _get_text(base, path, timeout=10.0):
    with urlrequest.urlopen(base + path, timeout=timeout) as resp:
        return resp.read().decode()


def _wait(pred, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"series {name} not exposed")


class _Driver:
    """Scheduler thread that records crashes instead of swallowing them."""

    def __init__(self, sched):
        self.sched = sched
        self.errors = []
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                if not self.sched.run_until_idle():
                    time.sleep(0.01)
            except Exception as e:  # noqa: BLE001
                self.errors.append(e)
                return

    def stop(self):
        self._stop.set()
        self._t.join(timeout=30)


def _bind_wire(pod, node):
    w = pod_to_wire(pod)
    w["nodeName"] = node
    return w


# ---------------------------------------------------------------------------
# (a) 5k-node MixedChurn + 10% silence: exactly-once eviction/reschedule
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("wire_plane", [
    "binary", pytest.param("json", marks=pytest.mark.slow)])
def test_hollow_5k_silence_evicts_and_reschedules_exactly_once(
        monkeypatch, wire_plane):
    """The PR-16 acceptance run. 5000 hollow nodes across 50 zones with
    churn running and 10% of the fleet permanently silent: the controller
    taints every silenced node, drains its pods through the rate-limited
    funnel, and the scheduler re-places each exactly once
    (``scheduler_eviction_requeues_total == apiserver_pod_evictions_total``
    — one requeue per eviction mutation, no lost pods, no duplicates).
    Pods on surviving nodes keep their placement. Per-zone the unhealthy
    fraction is ~10% (< threshold), so the wave runs at the primary rate.
    The binary wire plane is tier-1; json rides slow to prove the loop is
    codec-independent."""
    monkeypatch.setenv("TPU_SCHED_WIRE", wire_plane)
    server = APIServer()
    port = server.serve(0)
    base = f"http://127.0.0.1:{port}"
    prof = HollowProfile(
        count=5000, zones=50, heartbeat_s=1.5, drift=0.02,
        churn_per_s=1.0, churn_cordon_s=0.05, register_chunk=500,
        silence=0.10, silence_after_s=1.0, seed=7)
    plane = HollowNodePlane(base, prof)
    assert plane.register() == 5000
    sched_cs = HTTPClientset(base, sync_timeout=120.0)
    ctrl_cs = HTTPClientset(base, sync_timeout=120.0)
    sched = Scheduler(clientset=sched_cs, deterministic_ties=True)
    driver = _Driver(sched)
    ctrl = NodeLifecycleController(
        ctrl_cs, grace=3.0, noexec_after=0.75, tick=0.25,
        primary_qps=400.0, eviction_burst=64.0)
    try:
        plane.start()
        silent = set(plane.silent_nodes())
        assert len(silent) == 500
        assert plane.stats()["silenced"] == 500
        # Victims: pods direct-bound onto known-silent nodes (the silenced
        # set is deterministic from the profile seed). Survivors: pods
        # direct-bound onto healthy nodes — their placement is the oracle.
        silent_picks = sorted(silent)[:16]
        healthy_picks = [n for n in sorted(server.store.nodes)
                         if n not in silent][:24]
        victims = {}
        batch = []
        for i, node in enumerate(silent_picks * 3):   # 3 pods per node
            p = make_pod().name(f"victim-{i}").req(
                {"cpu": "50m", "memory": "32Mi"}).obj()
            victims[p.uid] = node
            batch.append(_bind_wire(p, node))
        survivors = {}
        for i, node in enumerate(healthy_picks):
            p = make_pod().name(f"survivor-{i}").req(
                {"cpu": "50m", "memory": "32Mi"}).obj()
            survivors[p.uid] = node
            batch.append(_bind_wire(p, node))
        _call(base, "POST", "/api/v1/pods", batch)
        ctrl.start()
        # the whole victim population drains through the eviction funnel
        _wait(lambda: server.pod_evictions >= len(victims),
              timeout=120, msg="eviction wave")
        # ...and every victim lands again, off the silenced fleet
        _wait(lambda: all(
            server.store.bindings.get(u) not in (None, "")
            and server.store.bindings[u] not in silent for u in victims),
            timeout=180, msg="re-placement off silenced nodes")
        # stop the controller (no new evictions), let the watch drain,
        # then hold the exactly-once ledger line
        ctrl.stop()
        _wait(lambda: sched.eviction_requeues == server.pod_evictions,
              timeout=60, msg="requeue/eviction counters to converge")
        assert sched.eviction_requeues == server.pod_evictions
        assert server.pod_evictions >= len(victims)
        # the ledger holds only the evicted-pending window: every victim
        # observed bound had its entry pruned by that re-bind (bounded
        # ledger — and a victim landing on a node that later fails stays
        # evictable). Each victim exists exactly once (dict-by-uid +
        # unique names).
        for uid, node in victims.items():
            if server.store.bindings.get(uid):
                assert uid not in server.evictions, uid
        for uid in list(server.evictions):
            assert uid in server.store.pods, uid
        names = [p.name for p in server.store.pods.values()
                 if p.name.startswith("victim-")]
        assert sorted(names) == sorted(set(names))
        assert len(names) == len(victims)
        # surviving fleet oracle-identical: any survivor whose node is
        # still in the fleet (churn deletes are legitimate GC evictions)
        # kept its original placement
        kept = 0
        for uid, node in survivors.items():
            if node in server.store.nodes and node not in silent:
                assert server.store.pods[uid].node_name == node, uid
                kept += 1
        assert kept >= len(survivors) // 2  # churn can't have eaten most
        # the acceptance metrics are exposed and carry the wave
        text = ctrl.metrics_text()
        assert _metric(text, "node_lifecycle_evictions_total") >= len(victims)
        assert _metric(text, "node_lifecycle_evictions_throttled_total") >= 0
        assert _metric(text, "node_lifecycle_taints_noexecute_total") > 0
        assert not driver.errors, f"scheduler crashed: {driver.errors!r}"
        assert plane.stats()["silenced_beats"] > 0  # silence really held
    finally:
        ctrl.stop()
        driver.stop()
        plane.stop()
        sched_cs.close()
        ctrl_cs.close()
        server.shutdown()


# ---------------------------------------------------------------------------
# (b) zone outage: FullDisruption throttles the dead zone, not the fleet
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_zone_outage_throttles_dead_zone_only():
    """An entire zone goes dark (outage_zone): its unhealthy fraction is
    1.0, so its eviction bucket drops to zero — a partitioned zone must
    read as an infrastructure failure, not 20 simultaneous node deaths.
    Pods in the dead zone stay bound (throttled, counted), while a lone
    silent node in a HEALTHY zone is still drained at the primary rate."""
    server = APIServer()
    port = server.serve(0)
    base = f"http://127.0.0.1:{port}"
    prof = HollowProfile(
        count=200, zones=10, heartbeat_s=0.5, drift=0.0, churn_per_s=0.0,
        silence=0.05, silence_after_s=0.5,
        outage_zone=3, outage_after_s=0.5, seed=11)
    plane = HollowNodePlane(base, prof)
    assert plane.register() == 200
    ctrl_cs = HTTPClientset(base)
    ctrl = NodeLifecycleController(
        ctrl_cs, grace=1.5, noexec_after=0.4, tick=0.2,
        primary_qps=50.0, eviction_burst=8.0)
    try:
        plane.start()
        silent = set(plane.silent_nodes())
        zone_of = {n: node.labels.get(ZONE_LABEL, "")
                   for n, node in server.store.nodes.items()}
        outage_nodes = sorted(n for n, z in zone_of.items()
                              if z == "zone-3")
        assert len(outage_nodes) == 20
        lone_silent = sorted(n for n in silent
                             if zone_of[n] != "zone-3")
        assert lone_silent, "profile seed put every silent node in zone-3?"
        # pods in the dead zone (must stay bound) + on the lone silent
        # node in a healthy zone (must drain)
        doomed_zone_pods, lone_pods, batch = {}, {}, []
        for i, node in enumerate(outage_nodes[:6]):
            p = make_pod().name(f"zonepod-{i}").req({"cpu": "50m"}).obj()
            doomed_zone_pods[p.uid] = node
            batch.append(_bind_wire(p, node))
        for i in range(4):
            p = make_pod().name(f"lone-{i}").req({"cpu": "50m"}).obj()
            lone_pods[p.uid] = lone_silent[0]
            batch.append(_bind_wire(p, lone_silent[0]))
        _call(base, "POST", "/api/v1/pods", batch)
        ctrl.start()
        # the dead zone trips FullDisruption...
        _wait(lambda: ctrl.evictor.zone_states.get("zone-3") == ZONE_FULL,
              msg="zone-3 FullDisruption")
        # ...while the lone silent node's pods drain at the primary rate
        _wait(lambda: all(
            server.store.pods[u].node_name == "" for u in lone_pods),
            msg="healthy-zone eviction wave")
        for uid in lone_pods:
            assert EVICTED_ANNOTATION in server.store.pods[uid].annotations
            assert server.evictions[uid] == intent_for(uid, lone_silent[0])
        # the throttle was observed (the dead zone had work but no token)
        _wait(lambda: ctrl.evictor.evictions_throttled_total >= 1,
              msg="throttle observations")
        # hold the line for a few reconciles: zone-3 pods never move
        time.sleep(1.0)
        for uid, node in doomed_zone_pods.items():
            assert server.store.pods[uid].node_name == node, uid
        assert server.pod_evictions == len(lone_pods)
        text = ctrl.metrics_text()
        assert _metric(text, "node_lifecycle_evictions_total") == len(
            lone_pods)
        assert _metric(text, "node_lifecycle_evictions_throttled_total") >= 1
        assert 'node_lifecycle_zone_state{zone="zone-3"} 2' in text
        # the lone silent node's zone stayed Normal
        assert ctrl.evictor.zone_states[zone_of[lone_silent[0]]] \
            == ZONE_NORMAL
    finally:
        ctrl.stop()
        plane.stop()
        ctrl_cs.close()
        server.shutdown()


# ---------------------------------------------------------------------------
# (c) leader kill9 mid-wave: the wave resumes with zero double-evictions
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_leader_kill9_mid_eviction_wave_zero_double_evictions(tmp_path):
    """SIGKILL the leader apiserver in the middle of an eviction wave.
    The promoted follower recovers the eviction ledger from the replicated
    WAL; the controller (whose clientset re-resolves the leader) first
    lifts taints — the fresh leader's heartbeat map makes the fleet look
    young, the designed post-failover posture — then re-degrades after one
    grace period and finishes the wave. Deterministic intents make every
    replay answer ``already=True``: each victim ends unbound-with-
    annotation exactly once, and replaying the full wave against the new
    leader mutates nothing."""
    from kubernetes_tpu.testing.faults import ReplicaSet

    LEASE = 1.5
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=LEASE, snapshot_every=100_000)
    hb_stop = threading.Event()
    ctrl = None
    ctrl_cs = None
    try:
        wcs = HTTPClientset(rs.follower_urls[0],
                            fallbacks=[rs.follower_urls[1]])
        writer = RetryingClientset(wcs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=40,
            seed=23))
        nodes = [make_node().name(f"n{i}")
                 .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                 .zone(f"z{i % 2}").obj() for i in range(8)]
        for n in nodes:
            writer.create_node(n)
        # victims: 10 pods bound across the two nodes that never heartbeat
        victims = {}
        for i in range(10):
            node = f"n{6 + (i % 2)}"
            p = make_pod().name(f"v{i}").req({"cpu": "100m"}).obj()
            victims[p.uid] = node
            _call(rs.leader_url, "POST", "/api/v1/pods",
                  _bind_wire(p, node))
        healthy = [f"n{i}" for i in range(6)]

        def heartbeat():
            # Beat every replica: followers answer 421 (swallowed), the
            # current leader — whoever that is — stamps the ages. Silent
            # nodes n6/n7 are never beaten on ANY leader.
            while not hb_stop.is_set():
                for r in list(rs.replicas):
                    try:
                        _call(r.url, "POST", "/api/v1/nodes/status",
                              {"names": healthy}, timeout=2.0)
                    except Exception:  # noqa: BLE001 - dead/following
                        pass
                hb_stop.wait(0.25)

        hb = threading.Thread(target=heartbeat, daemon=True)
        hb.start()
        ctrl_cs = HTTPClientset(
            rs.follower_urls[0],
            fallbacks=[rs.follower_urls[1], rs.leader_url])
        rcs = RetryingClientset(ctrl_cs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.5, max_attempts=20,
            seed=31))
        # slow wave: ~1.5 evictions/s so the kill lands mid-wave
        ctrl = NodeLifecycleController(
            rcs, grace=1.2, noexec_after=0.4, tick=0.2,
            primary_qps=1.5, eviction_burst=1.0)
        ctrl.start()
        _wait(lambda: ctrl.evictor.evictions_total >= 3,
              msg="wave under way")
        assert ctrl.evictor.evictions_total < len(victims)
        rs.kill9_leader()  # SIGKILL mid-wave: no flush, no goodbye
        new_leader = rs.wait_for_leader(timeout=LEASE * 6)
        assert new_leader == rs.follower_urls[0], new_leader
        # the wave RESUMES on the promoted leader: every victim ends
        # unbound with the eviction annotation
        def _all_drained():
            try:
                got = _call(new_leader, "GET", "/api/v1/pods", timeout=5)
            except Exception:  # noqa: BLE001
                return False
            by_name = {p["name"]: p for p in got
                       if p["name"].startswith("v")}
            return (len(by_name) == len(victims)
                    and all(not p["nodeName"] for p in by_name.values())
                    and all(EVICTED_ANNOTATION in (p.get("annotations")
                                                   or {})
                            for p in by_name.values()))
        _wait(_all_drained, timeout=90, msg="wave to resume and drain")
        ctrl.stop()
        # zero lost, zero duplicated pods
        got = _call(new_leader, "GET", "/api/v1/pods")
        names = [p["name"] for p in got if p["name"].startswith("v")]
        assert sorted(names) == sorted(set(names))
        assert len(names) == len(victims)
        # zero double-evictions: replaying the ENTIRE wave against the
        # promoted leader answers already=True for every victim and
        # mutates nothing (the ledger rode the replicated WAL)
        before = _get_text(new_leader, "/metrics")
        evicted_before = _metric(before, "apiserver_pod_evictions_total")
        for uid, node in victims.items():
            got = _call(new_leader, "POST",
                        f"/api/v1/pods/{uid}/eviction",
                        {"intent": intent_for(uid, node), "node": node})
            assert got.get("already") is True, (uid, got)
        after = _get_text(new_leader, "/metrics")
        assert _metric(after, "apiserver_pod_evictions_total") \
            == evicted_before
        assert _metric(after, "apiserver_pod_evictions_replayed_total") \
            >= len(victims)
        # the failover really interrupted the wave (post-promotion
        # taint-lift/re-degrade posture is allowed; double mutation is not)
        assert ctrl.evictor.evictions_total + \
            ctrl.evictor.evictions_replayed >= len(victims)
        st = rs.status(new_leader)
        assert st["role"] == "leader" and st["replEpoch"] >= 2
    finally:
        hb_stop.set()
        if ctrl is not None:
            ctrl.stop()
        if ctrl_cs is not None:
            ctrl_cs.close()
        try:
            wcs.close()
        except Exception:  # noqa: BLE001
            pass
        rs.stop()
