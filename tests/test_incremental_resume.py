"""Incremental session resume: typed event journal + delta plan rebuild.

The resume cache used to be all-or-nothing — ANY cluster event bumped
cluster_event_seq and forced a full snapshot→features teardown. The journal
(core/cache.py EventJournal) records what each bump was, so device sessions
classify intervening events and patch exactly the rows they dirtied
(models/tpu_scheduler.py _classify_delta/_apply_delta_patch) while keeping
the chained carry. These tests enforce the repo's core invariant on that
path: delta-patched sessions must produce assignments BIT-IDENTICAL to the
host oracle — and must demonstrably take the delta path (not the full-
rebuild fallback), including continuation across gate-lift and taint
events, with the fallback still engaging on unclassified events.
"""

import random

import pytest

from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _node(name, taint=None, cpu=8):
    b = (make_node().name(name)
         .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110})
         .zone(f"zone-{len(name) % 3}"))
    if taint:
        b = b.taint(*taint)
    return b.obj()


def _pod(name, ns="default", cpu="200m", gates=(), labels=None,
         tolerate=None):
    b = make_pod().name(name).namespace(ns).req({"cpu": cpu,
                                                 "memory": "128Mi"})
    for g in gates:
        b = b.scheduling_gate(g)
    if labels:
        b = b.labels(dict(labels))
    if tolerate:
        b = b.toleration(tolerate, "", "Exists", "NoSchedule")
    return b.obj()


def _pair(n_nodes=24, max_batch=64, taints=None, mesh=None):
    """(host oracle, device scheduler) over identical clusters. mesh=None:
    row patches target the single-device resident state. Under a sharded
    mesh, taint/alloc NODE updates patch through shardings-pinned jits
    (TestDeltaResumeUnderMesh) while pod events still decline to the full
    rebuild (their aggregates also ride the adopt seam)."""
    host = Scheduler(deterministic_ties=True)
    dev = TPUScheduler(max_batch=max_batch, mesh=mesh)
    # This suite asserts the SESSION path's delta machinery engages
    # (plan_rebuilds_delta, carry continuation). The score-hint fast path
    # (models/score_hints.py) would otherwise bind identical replicas
    # before any session starts — it has its own engagement + equivalence
    # suite in tests/test_hint_cache.py.
    dev._hints.enabled = False
    dev._hints.entry = None
    taints = taints or {}
    for s in (host, dev):
        for i in range(n_nodes):
            s.clientset.create_node(_node(f"node-{i}",
                                          taint=taints.get(i)))
    return host, dev


def _assignments(s):
    return {f"{p.namespace}/{p.name}": p.node_name
            for p in s.clientset.pods.values()}


def _both(host, dev, fn):
    """Apply one scripted step to both sides, then drain both."""
    fn(host)
    fn(dev)
    host.run_until_idle()
    dev.run_until_idle()


def _assert_identical(host, dev):
    a_h, a_d = _assignments(host), _assignments(dev)
    diffs = {k: (a_h[k], a_d.get(k)) for k in a_h if a_h[k] != a_d.get(k)}
    assert not diffs, f"host/device divergence after delta churn: {diffs}"


def _sessions(dev):
    """Every device session acquires its plan exactly once, under exactly
    one kind — the rebuild counters partition the session count."""
    return (dev.plan_rebuilds_full + dev.plan_rebuilds_delta
            + dev.plan_rebuilds_resume)


class TestDeltaResumeBetweenSessions:
    def test_bound_pod_delete_takes_delta_path(self):
        """WhileGated/DeletedPodsWithFinalizers shape: bound pods deleted
        between sessions must NOT force full plan rebuilds — the journal
        classifies pod_remove as a shrink row patch."""
        host, dev = _pair()
        victims = [_pod(f"victim-{i}") for i in range(10)]
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"victim-{i}")) for i in range(10)])
        del victims
        assert dev.plan_rebuilds_full == 1
        for r in range(4):
            def step(s, r=r):
                # delete one bound victim, then feed a new wave
                vs = [p for p in s.clientset.pods.values()
                      if p.name.startswith("victim-") and p.node_name]
                if vs:
                    s.clientset.delete_pod(
                        min(vs, key=lambda p: p.name))
                for i in range(6):
                    s.clientset.create_pod(_pod(f"wave{r}-{i}"))
            _both(host, dev, step)
        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full == 1, (
            "bound-pod deletes forced full rebuilds despite the journal")
        assert dev.plan_rebuilds_delta >= 4
        assert dev.delta_dirty_rows >= 4
        assert dev.host_path_pods == 0

    def test_gate_lift_is_benign_for_resume(self):
        """A scheduling-gate lift is queue-only: the saved plan+carry resume
        via the delta path with ZERO dirty rows."""
        host, dev = _pair()
        _both(host, dev, lambda s: s.clientset.create_pod(
            _pod("gated", gates=("hold",))))
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"before-{i}")) for i in range(8)])
        full0, rows0 = dev.plan_rebuilds_full, dev.delta_dirty_rows

        def lift(s):
            p = next(p for p in s.clientset.pods.values()
                     if p.name == "gated")
            p.scheduling_gates = []
            s.clientset.update_pod(p)
        _both(host, dev, lift)
        _assert_identical(host, dev)
        assert _assignments(dev)["default/gated"], "gated pod not scheduled"
        assert dev.plan_rebuilds_full == full0, (
            "gate lift tore the plan down")
        assert dev.plan_rebuilds_delta >= 1
        assert dev.delta_dirty_rows == rows0, "gate lift dirtied node rows"

    def test_taint_lift_and_taint_add_take_delta_path(self):
        """Taint-only node updates (labels untouched) row-patch the resident
        taint tensors: removal (shrink) and addition (strict, applied at the
        empty-pipeline session boundary) both keep the carry."""
        host, dev = _pair(taints={0: ("dedicated", "infra", "NoSchedule")})
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"a-{i}")) for i in range(8)])
        assert dev.plan_rebuilds_full == 1

        def lift_taint(s):
            s.clientset.update_node(_node("node-0"))  # fresh object, no taint
        _both(host, dev, lift_taint)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"b-{i}")) for i in range(8)])

        def add_taint(s):
            s.clientset.update_node(
                _node("node-3", taint=("dedicated", "infra", "NoSchedule")))
        _both(host, dev, add_taint)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"c-{i}")) for i in range(8)])

        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full == 1, (
            "taint-only node updates forced full rebuilds")
        assert dev.plan_rebuilds_delta >= 2
        assert dev.host_path_pods == 0
        # the untainted node is actually usable again (patch took effect)
        assert any(n == "node-0" for n in _assignments(dev).values())

    def test_unclassified_event_falls_back_to_full_rebuild(self):
        """Structural events (node add) are not delta-patchable: the session
        must fall back to the full snapshot→features rebuild — and still
        match the oracle."""
        host, dev = _pair(n_nodes=12)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"a-{i}")) for i in range(6)])
        full0 = dev.plan_rebuilds_full
        _both(host, dev, lambda s: s.clientset.create_node(_node("node-99")))
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"b-{i}")) for i in range(6)])
        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full > full0, (
            "structural event did not fall back to the full rebuild")


class TestDeltaResumeUnderMesh:
    """Mesh-first device plane: under a sharded mesh, EVERY classifiable
    journal kind — taint/alloc NODE updates AND the POD-event aggregates
    that dominate churn — delta-patches the session through jits pinned to
    the committed shardings (parallel/mesh.py mesh_state_shardings on the
    row scatter, ops/kernel.py patch_carry_rows_pinned on the carry, both
    donating the stale buffers). The resident mirror copy IS the sharded
    state (NodeStateMirror.commit_shardings), so adopt/resume never
    round-trip the whole state through the host."""

    def test_taint_updates_take_delta_path_under_mesh(self):
        from kubernetes_tpu.parallel import make_mesh
        host, dev = _pair(taints={0: ("dedicated", "infra", "NoSchedule")},
                          mesh=make_mesh(n_cells=1))
        assert dev.mesh is not None
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"a-{i}")) for i in range(8)])
        assert dev.plan_rebuilds_full == 1

        def lift_taint(s):
            s.clientset.update_node(_node("node-0"))  # fresh object, no taint
        _both(host, dev, lift_taint)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"b-{i}")) for i in range(8)])

        def add_taint(s):
            s.clientset.update_node(
                _node("node-3", taint=("dedicated", "infra", "NoSchedule")))
        _both(host, dev, add_taint)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"c-{i}")) for i in range(8)])

        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full == 1, (
            "taint-only node updates forced full rebuilds under the mesh")
        assert dev.plan_rebuilds_delta >= 2
        assert dev.host_path_pods == 0
        assert any(n == "node-0" for n in _assignments(dev).values())

    def test_pod_events_take_delta_path_under_mesh(self):
        """The tentpole inversion: a bound-pod delete (pod_remove) between
        mesh sessions row-patches the SHARDED state + carry — zero full
        rebuilds on the patchable POD kind — and stays bit-identical to
        the host oracle."""
        from kubernetes_tpu.parallel import make_mesh
        host, dev = _pair(mesh=make_mesh(n_cells=1))
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"a-{i}")) for i in range(8)])
        full0, delta0 = dev.plan_rebuilds_full, dev.plan_rebuilds_delta

        def delete_one(s):
            vs = [p for p in s.clientset.pods.values() if p.node_name]
            s.clientset.delete_pod(min(vs, key=lambda p: p.name))
        _both(host, dev, delete_one)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"b-{i}")) for i in range(8)])
        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full == full0, (
            "patchable POD event forced a full rebuild under the mesh")
        assert dev.plan_rebuilds_delta > delta0
        assert dev.host_path_pods == 0
        # the sharded resident really is the session state: one committed
        # placement, no per-session device_put round-trip
        assert dev.mirror._shardings is not None

    def test_mesh_churn_fuzz_zero_full_rebuilds_on_patchable_events(self):
        """Churn-equivalence fuzz delta-ENGAGED on the virtual 8-device
        mesh (acceptance): after the first session, a stream of ONLY
        patchable events — bound-pod deletes (shrink), pod adds, taint
        flips — must produce ZERO further full rebuilds, with assignments
        bit-identical to the always-rebuild host oracle."""
        import random
        from kubernetes_tpu.parallel import make_mesh
        rng = random.Random(7)
        host, dev = _pair(n_nodes=16, mesh=make_mesh(n_cells=1))
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}", tolerate="dedicated")) for i in range(8)])
        assert dev.plan_rebuilds_full == 1
        for r in range(10):
            op = rng.random()
            if op < 0.4:
                def kill(s):
                    bound = sorted((p for p in s.clientset.pods.values()
                                    if p.node_name),
                                   key=lambda p: (p.namespace, p.name))
                    if bound:
                        s.clientset.delete_pod(bound[0])
                _both(host, dev, kill)
            elif op < 0.7:
                i = rng.randint(0, 15)
                tainted = rng.random() < 0.5
                _both(host, dev, lambda s, i=i, t=tainted:
                      s.clientset.update_node(_node(
                          f"node-{i}",
                          taint=("dedicated", "x", "NoSchedule")
                          if t else None)))
            k = rng.randint(2, 5)
            _both(host, dev, lambda s, r=r, k=k: [s.clientset.create_pod(
                _pod(f"w{r}-{i}", tolerate="dedicated"))
                for i in range(k)])
        _assert_identical(host, dev)
        assert dev.failures == host.failures == 0
        assert dev.plan_rebuilds_full == 1, (
            "a patchable event stream forced full rebuilds under the mesh")
        assert dev.plan_rebuilds_delta >= 3
        assert dev.host_path_pods == 0

    def test_donated_resident_never_read_after_patch(self):
        """Donation safety (the pjit donate_argnums contract): the patch
        seam donates the stale sharded state/carry into the pinned jits —
        the OLD buffers must be deleted (reused in place) and never read
        again; the rebound resident keeps serving sessions correctly."""
        from kubernetes_tpu.parallel import make_mesh
        host, dev = _pair(mesh=make_mesh(n_cells=1))
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"a-{i}")) for i in range(8)])
        old_state = dev.mirror._device
        old_req = old_state.req_r

        def delete_one(s):
            vs = [p for p in s.clientset.pods.values() if p.node_name]
            s.clientset.delete_pod(min(vs, key=lambda p: p.name))
        _both(host, dev, delete_one)
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"b-{i}")) for i in range(8)])
        assert dev.plan_rebuilds_delta >= 1, "delta patch did not engage"
        # the patch rebound the resident; donation deleted the old buffers
        assert dev.mirror._device is not old_state
        assert old_req.is_deleted(), (
            "stale sharded state was not donated into the patch jit")
        _assert_identical(host, dev)

    def test_patch_rows_declines_on_deleted_resident(self):
        """A resident whose buffers were donated back to a kernel must
        make patch_rows return None (→ full-rebuild fallback), never read
        the deleted arrays."""
        from kubernetes_tpu.parallel import make_mesh
        _host, dev = _pair(mesh=make_mesh(n_cells=1))
        for i in range(4):
            dev.clientset.create_pod(_pod(f"a-{i}"))
        dev.run_until_idle()
        mirror = dev.mirror
        assert mirror._device is not None
        ni = dev.cache.nodes.get("node-0")
        # simulate the donation: delete one resident leaf out from under it
        mirror._device.req_r.delete()
        assert mirror.patch_rows([(0, ni)]) is None
        # ... and the forced full flush recovers from staging truth
        state = mirror.flush()
        assert not state.req_r.is_deleted()


class TestMidSessionContinuation:
    """Events arriving THROUGH the inbox while a session is live (the
    threaded watch seam) must continue the session — carry intact, no
    teardown — when the journal classifies them."""

    def _park(self, dev, fn):
        """Park a clientset mutation as an off-thread watch delivery: the
        session's refill drains the inbox and runs it on the loop thread."""
        dev._event_inbox.append((lambda: fn(dev), ()))

    def test_session_continues_across_parked_gate_lift(self):
        host, dev = _pair()
        gated = {}
        def mk_gated(s):
            p = _pod("gated", gates=("hold",))
            gated[id(s)] = p
            s.clientset.create_pod(p)
        _both(host, dev, mk_gated)
        for s in (host, dev):
            for i in range(12):
                s.clientset.create_pod(_pod(f"w1-{i}"))

        def lift(s):
            p = gated[id(s)]
            p.scheduling_gates = []
            s.clientset.update_pod(p)
        self._park(dev, lift)
        dev.run_until_idle()
        lift(host)
        host.run_until_idle()
        _assert_identical(host, dev)
        assert _assignments(dev)["default/gated"]
        # ONE session: one full build, gate lift consumed mid-session
        # (benign advance — no extra plan acquisition of any kind).
        assert dev.plan_rebuilds_full == 1
        assert _sessions(dev) == 1, "gate lift ended the live session"

    def test_session_continues_across_parked_pod_delete(self):
        host, dev = _pair()
        _both(host, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        assert dev.plan_rebuilds_full == 1
        for s in (host, dev):
            for i in range(12):
                s.clientset.create_pod(_pod(f"w1-{i}"))

        def kill_seed(s):
            p = next(p for p in s.clientset.pods.values()
                     if p.name == "seed-0")
            s.clientset.delete_pod(p)
            for i in range(12):
                s.clientset.create_pod(_pod(f"w2-{i}"))
        self._park(dev, kill_seed)
        dev.run_until_idle()
        kill_seed(host)
        host.run_until_idle()
        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full == 1, (
            "mid-session bound-pod delete tore the session down")
        assert dev.plan_rebuilds_delta >= 1
        assert dev.host_path_pods == 0


class TestNeutralSignatureBatching:
    def test_cross_namespace_pods_share_one_session(self):
        """Pods identical except labels+namespace (the *WithNSSelector init
        shape) must ride ONE session/plan when nothing in the cluster
        carries affinity terms — not one full rebuild per namespace."""
        host, dev = _pair()

        def create(s):
            for n in range(5):
                for i in range(8):
                    s.clientset.create_pod(
                        _pod(f"p-{i}", ns=f"ns-{n}",
                             labels={"team": f"t{n}"}))
        _both(host, dev, create)
        _assert_identical(host, dev)
        assert dev.device_scheduled == 40
        assert dev.plan_rebuilds_full == 1, (
            "per-namespace signatures fragmented the session")
        assert dev.device_batches == 1

    def test_neutral_batching_disabled_when_affinity_pods_exist(self):
        """One affinity-carrying pod in the cluster makes labels/namespace
        scheduling-relevant: neutral batching must switch off (correctness
        over speed) and assignments must still match the oracle."""
        host, dev = _pair()

        def create(s):
            s.clientset.create_pod(
                make_pod().name("anchor").req({"cpu": "100m"})
                .label("color", "red")
                .pod_affinity("kubernetes.io/hostname", {"color": "red"},
                              anti=True).obj())
            for n in range(3):
                for i in range(4):
                    s.clientset.create_pod(_pod(f"p-{i}", ns=f"ns-{n}"))
        _both(host, dev, create)
        _assert_identical(host, dev)
        assert dev.plan_rebuilds_full >= 3, (
            "neutral batching engaged with affinity pods live")


class TestChurnFuzz:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_mixed_churn_oracle_equivalence(self, seed):
        """MixedChurn-style randomized event stream: gate lifts, bound-pod
        deletes, taint flips, namespace sweeps, and (rarely) node adds,
        interleaved with scheduling. Assignments must be bit-identical to
        the host oracle, the delta path must demonstrably engage, and the
        full-rebuild fallback must engage on the structural events."""
        rng = random.Random(seed)
        host, dev = _pair(n_nodes=16)
        gated = []
        seq = 0

        def create_wave(s, wave, ns, k, gate):
            # Fuzz pods tolerate the churn taint: the taint flips still
            # exercise the EV_NODE_UPDATE row-patch path, but never strand
            # pods as unschedulable (whose retry attempts would perturb the
            # resume key every cycle and mask the delta path).
            for i in range(k):
                s.clientset.create_pod(
                    _pod(f"f{wave}-{i}", ns=ns, tolerate="dedicated",
                         gates=("hold",) if gate else ()))

        for _ in range(14):
            op = rng.random()
            if op < 0.35:
                k, ns = rng.randint(2, 6), rng.choice(
                    ["default", "ns-a", "ns-b"])
                g = rng.random() < 0.25
                if g:
                    gated.append(f"f{seq}-")
                _both(host, dev, lambda s, w=seq, k=k, ns=ns, g=g:
                      create_wave(s, w, ns, k, g))
                seq += 1
            elif op < 0.55:
                def kill(s):
                    bound = sorted((p for p in s.clientset.pods.values()
                                    if p.node_name and not p.pod_group),
                                   key=lambda p: (p.namespace, p.name))
                    if bound:
                        s.clientset.delete_pod(bound[0])
                _both(host, dev, kill)
            elif op < 0.70 and gated:
                prefix = gated.pop(0)
                def lift(s, prefix=prefix):
                    for p in list(s.clientset.pods.values()):
                        if p.name.startswith(prefix) and p.scheduling_gates:
                            p.scheduling_gates = []
                            s.clientset.update_pod(p)
                _both(host, dev, lift)
            elif op < 0.93:
                i = rng.randint(0, 15)
                tainted = rng.random() < 0.5
                def flip(s, i=i, tainted=tainted):
                    s.clientset.update_node(_node(
                        f"node-{i}",
                        taint=("dedicated", "x", "NoSchedule")
                        if tainted else None))
                _both(host, dev, flip)
            else:
                name = f"extra-{seq}"
                seq += 1
                _both(host, dev,
                      lambda s, name=name: s.clientset.create_node(
                          _node(name)))
        # drain any still-gated stragglers so the comparison is total
        def lift_all(s):
            for p in list(s.clientset.pods.values()):
                if p.scheduling_gates:
                    p.scheduling_gates = []
                    s.clientset.update_pod(p)
        _both(host, dev, lift_all)
        # Deterministic delta tail (a random stream can legitimately put a
        # structural event before every session — correct, but then the
        # delta path never samples): one clean wave to establish the resume
        # carry, then a shrink event + wave that must ride it.
        _both(host, dev, lambda s: create_wave(s, "tail0", "default", 4,
                                               False))
        delta0 = dev.plan_rebuilds_delta

        def shrink_step(s):
            bound = sorted((p for p in s.clientset.pods.values()
                            if p.node_name),
                           key=lambda p: (p.namespace, p.name))
            s.clientset.delete_pod(bound[0])
            create_wave(s, "tail1", "default", 4, False)
        _both(host, dev, shrink_step)
        assert dev.plan_rebuilds_delta > delta0, (
            "shrink event after a clean session did not take the delta path")
        # ... and a structural event must take the full-rebuild fallback.
        full0 = dev.plan_rebuilds_full

        def structural_step(s):
            s.clientset.create_node(_node("tail-node"))
            create_wave(s, "tail2", "default", 4, False)
        _both(host, dev, structural_step)
        _assert_identical(host, dev)
        assert dev.failures == host.failures == 0
        assert dev.plan_rebuilds_full > full0, (
            "structural event did not fall back to the full rebuild")
        assert dev.device_scheduled > 0
        assert dev.host_path_pods == 0
