"""Overload & fairness suite (docs/RESILIENCE.md § overload & fairness):

- core/flowcontrol.py units — shuffle-shard collision bounds, weighted
  round-robin dequeue proportions, exempt-lane bypass under saturation;
- the HTTP shed contract — queue-full 429 + Retry-After envelope against
  a LIVE apiserver, with the shed path never blocking on the write lock;
- the client half — core/backoff.py honors Retry-After with decorrelated
  jitter, and a RetryingClientset rides a shed to eventual success;
- core/queue.py per-tenant fair dequeue — proportions, within-tenant
  order preservation, and the starvation gauge.
"""

import threading
import time
from urllib.error import HTTPError

import pytest

from kubernetes_tpu.core.backoff import (RetryConfig, is_retriable,
                                         retry_after_of, retry_call)
from kubernetes_tpu.core.flowcontrol import (EXEMPT, WORKLOAD, FlowController,
                                             PriorityLevel, default_levels,
                                             shuffle_shard_hand)


def _err429(retry_after="2"):
    headers = {"Retry-After": retry_after} if retry_after is not None else {}
    return HTTPError("http://x/api/v1/pods", 429, "Too Many Requests",
                     headers, None)


# ---------------------------------------------------------------------------
# shuffle sharding
# ---------------------------------------------------------------------------


class TestShuffleSharding:
    def test_hand_is_distinct_and_stable(self):
        for flow in ("tenant-a", "tenant-b", "flood"):
            hand = shuffle_shard_hand(WORKLOAD, flow, 8, 2)
            assert len(hand) == len(set(hand)) == 2
            assert all(0 <= i < 8 for i in hand)
            # deterministic: same flow, same hand, every call/process
            assert hand == shuffle_shard_hand(WORKLOAD, flow, 8, 2)

    def test_collision_bound(self):
        """The isolation claim: a flood flow's hand pins only ITS queues.
        Over many tenants, the share whose entire hand lands inside the
        flood's hand must stay near (hand/queues)^hand — with 8 queues and
        hand 2 that is ~(2/8)^2 ≈ 6%; assert a generous 15% bound."""
        queues, hand_size = 8, 2
        flood = set(shuffle_shard_hand(WORKLOAD, "flood", queues, hand_size))
        trapped = sum(
            1 for i in range(400)
            if set(shuffle_shard_hand(WORKLOAD, f"ns-{i}", queues,
                                      hand_size)) <= flood)
        assert trapped / 400 < 0.15, trapped

    def test_level_scoping_changes_hands(self):
        # The same flow key in different levels deals independent hands
        # (statistically; assert they differ for at least one probe flow).
        assert any(
            shuffle_shard_hand("workload", f"ns-{i}", 16, 2)
            != shuffle_shard_hand("system", f"ns-{i}", 16, 2)
            for i in range(8))


# ---------------------------------------------------------------------------
# priority levels: WRR proportions + exempt bypass + shed accounting
# ---------------------------------------------------------------------------


class TestPriorityLevel:
    def _saturated_level(self, weights):
        lvl = PriorityLevel(WORKLOAD, seats=1, queues=8, queue_length=64,
                            hand_size=2, max_wait=5.0, flow_weights=weights)
        lvl.seats_in_use = 1  # the seat is taken; everyone below queues
        return lvl

    def test_weighted_dequeue_proportions(self):
        """Smooth WRR: with weights 3:1 and both flows saturated, service
        counts converge to 3:1 (exact over any window of 4 rounds)."""
        lvl = self._saturated_level({"gold": 3.0, "bronze": 1.0})
        for _ in range(40):
            assert lvl._enqueue("gold") is not None
            assert lvl._enqueue("bronze") is not None
        served = {"gold": 0, "bronze": 0}
        for _ in range(40):
            lvl.seats_in_use -= 1  # release
            before = {f: served[f] for f in served}
            lvl._dispatch_next()
            # exactly one waiter seated per free seat
            assert lvl.seats_in_use == 1
            for q in lvl._queues:
                pass
            seated = [w for q in lvl._queues for w in q]
            # count by elimination: 80 - len(still queued) - already served
            total_served = 80 - len(seated)
            got = total_served - sum(before.values())
            assert got == 1
            # attribute: find which flow shrank
            remaining = {"gold": 0, "bronze": 0}
            for w in seated:
                remaining[w.flow] += 1
            for f in served:
                served[f] = 40 - remaining[f]
        assert served["gold"] == 30 and served["bronze"] == 10, served

    def test_queue_full_sheds(self):
        lvl = PriorityLevel(WORKLOAD, seats=1, queues=4, queue_length=2,
                            hand_size=1, max_wait=0.1)
        lvl.seats_in_use = 1
        flow = "flood"
        assert lvl._enqueue(flow) is not None
        assert lvl._enqueue(flow) is not None
        assert lvl._enqueue(flow) is None  # its one queue is full

    def test_flood_cannot_fill_foreign_queues(self):
        """A flood saturating its own hand leaves the other queues — and
        therefore other tenants — untouched."""
        lvl = PriorityLevel(WORKLOAD, seats=1, queues=8, queue_length=4,
                            hand_size=2, max_wait=0.1)
        lvl.seats_in_use = 1
        while lvl._enqueue("flood") is not None:
            pass
        assert lvl.queue_depth() <= 2 * 4  # bounded by the flood's hand
        # a well-behaved tenant outside the flood's hand still queues
        hand_flood = set(shuffle_shard_hand(WORKLOAD, "flood", 8, 2))
        victim = next(f"ns-{i}" for i in range(64)
                      if not set(shuffle_shard_hand(WORKLOAD, f"ns-{i}",
                                                    8, 2)) & hand_flood)
        assert lvl._enqueue(victim) is not None


class TestFlowController:
    def test_exempt_bypass_under_saturation(self):
        fc = FlowController({
            EXEMPT: PriorityLevel(EXEMPT, queues=0),
            WORKLOAD: PriorityLevel(WORKLOAD, seats=1, queues=1,
                                    queue_length=1, hand_size=1,
                                    max_wait=0.05),
        })
        seat = fc.admit(WORKLOAD, "ns-a")
        assert seat is not None and seat.seated
        # workload is saturated: one waiter queues (and will time out),
        # the next sheds instantly...
        t0 = time.monotonic()
        assert fc.admit(WORKLOAD, "ns-a") is None  # waited max_wait, shed
        assert time.monotonic() - t0 < 1.0
        # ...but the exempt lane admits instantly, every time.
        for _ in range(32):
            t1 = time.monotonic()
            ticket = fc.admit(EXEMPT, "control")
            assert ticket is not None
            assert time.monotonic() - t1 < 0.05
            fc.release(ticket)  # no seat held; must be a no-op
        snap = fc.snapshot()
        assert snap[EXEMPT]["dispatched"] == 32
        assert snap[EXEMPT]["rejected"] == 0
        assert snap[WORKLOAD]["rejected"] >= 1
        fc.release(seat)

    def test_release_dispatches_queued_waiter(self):
        fc = FlowController({
            WORKLOAD: PriorityLevel(WORKLOAD, seats=1, queues=2,
                                    queue_length=4, hand_size=1,
                                    max_wait=5.0)})
        first = fc.admit(WORKLOAD, "ns-a")
        got = {}

        def queued():
            got["ticket"] = fc.admit(WORKLOAD, "ns-b")

        t = threading.Thread(target=queued, daemon=True)
        t.start()
        time.sleep(0.1)
        assert "ticket" not in got  # parked in the fair queue
        fc.release(first)
        t.join(timeout=5)
        assert got["ticket"] is not None and got["ticket"].seated
        fc.release(got["ticket"])
        snap = fc.snapshot()
        assert snap[WORKLOAD]["queued"] == 1
        assert snap[WORKLOAD]["dispatched"] == 2
        assert snap[WORKLOAD]["seats"] == 0

    def test_classification(self):
        fc = FlowController(default_levels())
        assert fc.classify("PUT", "/api/v1/leases/shard-0") == (EXEMPT,
                                                                "control")
        assert fc.classify("POST", "/replication/leader")[0] == EXEMPT
        assert fc.classify("POST", "/api/v1/nodes/status")[0] == "system"
        assert fc.classify("POST", "/api/v1/pods", "team-a") == (WORKLOAD,
                                                                 "team-a")
        assert fc.classify("POST", "/api/v1/bindings", "") == (WORKLOAD,
                                                               "default")

    def test_retry_after_scales_with_depth(self):
        fc = FlowController({
            WORKLOAD: PriorityLevel(WORKLOAD, seats=1, queues=1,
                                    queue_length=8, hand_size=1,
                                    max_wait=1.0)})
        base = fc.retry_after(WORKLOAD)
        assert base >= 1
        lvl = fc.levels[WORKLOAD]
        lvl.seats_in_use = 1
        for _ in range(8):
            lvl._enqueue("flood")
        assert fc.retry_after(WORKLOAD) >= base


# ---------------------------------------------------------------------------
# the client half: 429 + Retry-After through core/backoff.py
# ---------------------------------------------------------------------------


class TestClientBackoff:
    def test_429_is_retriable_and_parsed(self):
        e = _err429("3")
        assert is_retriable(e)
        assert retry_after_of(e) == 3.0
        assert retry_after_of(_err429(None)) is None
        assert retry_after_of(_err429("garbage")) is None
        assert retry_after_of(HTTPError("u", 404, "nope", {}, None)) is None

    def test_retry_after_floor_and_decorrelated_jitter(self):
        """Sleeps honor the server's hint as a FLOOR, spread with
        decorrelated jitter (never the bare exponential schedule), grow
        against persistent sheds, and stay under the cap."""
        sleeps = []
        calls = {"n": 0}

        def shed_twice():
            calls["n"] += 1
            if calls["n"] <= 3:
                raise _err429("2")
            return "ok"

        cfg = RetryConfig(initial_backoff=0.001, max_backoff=0.01,
                          max_attempts=5, seed=7, retry_after_cap=30.0)
        assert retry_call(shed_twice, cfg, sleep=sleeps.append) == "ok"
        assert len(sleeps) == 3
        for d in sleeps:
            assert 2.0 <= d <= 30.0  # floor = the hint, cap respected
        # decorrelated: successive sleeps differ (no synchronized herd)
        assert len(set(sleeps)) == len(sleeps)
        # deterministic per seed (chaos replay contract)
        calls["n"] = 0
        replay = []
        retry_call(shed_twice, cfg, sleep=replay.append)
        assert replay == sleeps

    def test_retry_after_cap_bounds_hostile_header(self):
        sleeps = []
        calls = {"n": 0}

        def shed_once():
            calls["n"] += 1
            if calls["n"] == 1:
                raise _err429("86400")  # a day — hostile/buggy
            return "ok"

        cfg = RetryConfig(max_attempts=3, seed=1, retry_after_cap=5.0)
        assert retry_call(shed_once, cfg, sleep=sleeps.append) == "ok"
        assert sleeps == [5.0]

    def test_budget_still_bounds_attempts(self):
        cfg = RetryConfig(max_attempts=3, seed=0)
        calls = {"n": 0}

        def always_shed():
            calls["n"] += 1
            raise _err429("1")

        with pytest.raises(HTTPError):
            retry_call(always_shed, cfg, sleep=lambda d: None)
        assert calls["n"] == 3


# ---------------------------------------------------------------------------
# the HTTP shed contract against a live apiserver
# ---------------------------------------------------------------------------


def _tiny_controller(max_wait=2.0):
    return FlowController({
        EXEMPT: PriorityLevel(EXEMPT, queues=0),
        "system": PriorityLevel("system", seats=4, queues=4,
                                queue_length=64, hand_size=1),
        WORKLOAD: PriorityLevel(WORKLOAD, seats=1, queues=1, queue_length=1,
                                hand_size=1, max_wait=max_wait),
    })


class TestHTTPShedEnvelope:
    def test_queue_full_429_with_retry_after(self):
        """Saturate a 1-seat/1-queue workload lane by parking the write
        plane: the first POST holds the seat (blocked on _write_lock), the
        second queues, the third sheds 429 with Retry-After — served
        entirely off the write lock, while the exempt lane (lease CAS)
        keeps landing."""
        import http.client

        from kubernetes_tpu.core.apiserver import APIServer, pod_to_wire
        from kubernetes_tpu.core import wire
        from kubernetes_tpu.testing.wrappers import make_pod

        api = APIServer()
        api.flowcontrol = _tiny_controller()
        port = api.serve(0)
        results = []

        def post(i):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            try:
                body = wire.jdumps(pod_to_wire(
                    make_pod().name(f"p{i}").req({"cpu": "100m"})
                    .obj())).encode()
                conn.request("POST", "/api/v1/pods", body=body)
                resp = conn.getresponse()
                results.append((resp.status, resp.getheader("Retry-After")))
                resp.read()
            finally:
                conn.close()

        api._write_lock.acquire()  # park the write plane
        try:
            threads = []
            for i in range(2):  # seat holder + one queued waiter
                t = threading.Thread(target=post, args=(i,), daemon=True)
                t.start()
                threads.append(t)
                time.sleep(0.2)
            # the lane is saturated: this one must shed, FAST, with the
            # envelope — even though the write lock is still held.
            t0 = time.monotonic()
            post(99)
            shed_latency = time.monotonic() - t0
            assert shed_latency < 1.0, shed_latency
            status, ra = results[-1]
            assert status == 429
            assert ra is not None and int(ra) >= 1
            # exempt lane unaffected by the saturation: lease CAS lands
            # (it serializes under the write lock itself, so just assert
            # admission-side accounting here, not the full round trip).
            assert api.flowcontrol.admit(EXEMPT, "control") is not None
        finally:
            api._write_lock.release()
        for t in threads:
            t.join(timeout=30)
        # seat holder + queued waiter both landed once the plane freed
        codes = sorted(s for s, _ in results)
        assert codes == [201, 201, 429], codes
        snap = api.flowcontrol.snapshot()
        assert snap[WORKLOAD]["rejected"] == 1
        assert snap[WORKLOAD]["dispatched"] == 2
        assert snap[WORKLOAD]["seats"] == 0
        m = api.expose_metrics()
        assert ('apiserver_flowcontrol_rejected_total'
                '{priority_level="workload"} 1') in m
        api.shutdown()

    def test_retrying_clientset_rides_shed_to_success(self):
        """A shed write backs off per Retry-After and lands on the next
        try — the live-server client-backoff test: RetryingClientset +
        HTTPClientset against a saturated lane that frees mid-backoff."""
        from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset
        from kubernetes_tpu.core.clientset import RetryingClientset
        from kubernetes_tpu.testing.wrappers import make_pod

        api = APIServer()
        api.flowcontrol = _tiny_controller(max_wait=0.2)
        port = api.serve(0)
        http_cs = HTTPClientset(f"http://127.0.0.1:{port}")
        rcs = RetryingClientset(http_cs, retry=RetryConfig(
            initial_backoff=0.01, max_backoff=0.1, max_attempts=8, seed=3,
            retry_after_cap=3.0))
        try:
            # Saturate the 1-seat lane: a slow POST holds the seat while
            # the write lock is parked; queue_length=1 fills with one more.
            api._write_lock.acquire()
            blockers = []

            def hold(i):
                try:
                    http_cs._call("POST", "/api/v1/pods",
                                  __import__(
                                      "kubernetes_tpu.core.apiserver",
                                      fromlist=["pod_to_wire"]).pod_to_wire(
                                      make_pod().name(f"h{i}")
                                      .req({"cpu": "1m"}).obj()))
                except Exception:  # noqa: BLE001 - may shed; irrelevant
                    pass

            for i in range(2):
                t = threading.Thread(target=hold, args=(i,), daemon=True)
                t.start()
                blockers.append(t)
                time.sleep(0.2)

            def free_later():
                time.sleep(1.0)
                api._write_lock.release()

            threading.Thread(target=free_later, daemon=True).start()
            # This create sheds (lane saturated), backs off per
            # Retry-After, and succeeds once the plane frees.
            rcs.create_pod(make_pod().name("measured")
                           .req({"cpu": "100m"}).obj())
            assert rcs.retries_total >= 1
            assert api.store.pods  # it landed
            assert any(p.name == "measured"
                       for p in api.store.pods.values())
            snap = api.flowcontrol.snapshot()
            assert snap[WORKLOAD]["rejected"] >= 1
            for t in blockers:
                t.join(timeout=30)
        finally:
            http_cs.close()
            api.shutdown()


# ---------------------------------------------------------------------------
# scheduler queue: per-tenant fair dequeue + starvation accounting
# ---------------------------------------------------------------------------


class TestFairTenantQueue:
    def _queue(self, weights=None):
        from kubernetes_tpu.core.queue import PriorityQueue
        return PriorityQueue(fair_tenant_dequeue=True,
                             tenant_weights=weights)

    def _pod(self, name, ns, priority=0):
        from kubernetes_tpu.testing.wrappers import make_pod
        return (make_pod().name(name).namespace(ns)
                .req({"cpu": "100m"}).priority(priority).obj())

    def test_wrr_proportions_under_synthetic_load(self):
        q = self._queue(weights={"gold": 3.0, "bronze": 1.0})
        for i in range(40):
            q.add(self._pod(f"g{i}", "gold"))
            q.add(self._pod(f"b{i}", "bronze"))
        served = {"gold": 0, "bronze": 0}
        for _ in range(40):
            qpi = q.pop()
            served[qpi.pod.namespace] += 1
            q.done(qpi.uid)
        assert served == {"gold": 30, "bronze": 10}, served

    def test_flood_cannot_starve_other_tenants(self):
        """10k flood pods vs 10 well-behaved ones: equal weights mean the
        well-behaved tenant's pods all pop inside the first 2N cycles."""
        q = self._queue()
        for i in range(2000):
            q.add(self._pod(f"f{i}", "flood"))
        for i in range(10):
            q.add(self._pod(f"w{i}", "web"))
        seen_web = 0
        for cycle in range(40):
            qpi = q.pop()
            if qpi.pod.namespace == "web":
                seen_web += 1
            q.done(qpi.uid)
        assert seen_web == 10  # all well-behaved pods served in 40 cycles

    def test_within_tenant_priority_order_preserved(self):
        """The fair heap only changes WHICH tenant pops next; inside a
        tenant the framework's queue-sort order (PrioritySort) holds."""
        from kubernetes_tpu.core.node_info import PodInfo
        from kubernetes_tpu.core.queue import QueuedPodInfo, _FairTenantHeap
        from kubernetes_tpu.plugins.basic import PrioritySort

        ps = PrioritySort()
        heap = _FairTenantHeap(ps.less, sort_key=PrioritySort.sort_key)
        for name, prio in (("lo", 1), ("hi", 100), ("mid", 50)):
            heap.push(QueuedPodInfo(
                pod_info=PodInfo.of(self._pod(name, "a", priority=prio)),
                timestamp=1.0))
        assert [heap.pop().pod.name for _ in range(3)] == ["hi", "mid", "lo"]

    def test_heap_interface_parity(self):
        q = self._queue()
        p = self._pod("x", "a")
        q.add(p)
        assert q.active_q.get(p.uid) is not None
        assert p.uid in q.active_q
        assert len(q.active_q) == 1
        q.delete(p)
        assert q.active_q.get(p.uid) is None
        assert len(q.active_q) == 0
        assert q.pop() is None

    def test_starvation_by_namespace(self):
        clock = {"t": 100.0}
        from kubernetes_tpu.core.queue import PriorityQueue
        q = PriorityQueue(fair_tenant_dequeue=True,
                          now=lambda: clock["t"])
        q.add(self._pod("a0", "alpha"))
        clock["t"] = 105.0
        q.add(self._pod("b0", "beta"))
        clock["t"] = 110.0
        starve = q.starvation_by_namespace()
        assert starve["alpha"] == pytest.approx(10.0)
        assert starve["beta"] == pytest.approx(5.0)
        qpi = q.pop()  # WRR serves one of them
        q.done(qpi.uid)
        starve = q.starvation_by_namespace()
        assert len(starve) == 1  # the served tenant's entry drained

    def test_plain_queue_starvation_also_works(self):
        from kubernetes_tpu.core.queue import PriorityQueue
        clock = {"t": 0.0}
        q = PriorityQueue(now=lambda: clock["t"])
        q.add(self._pod("p", "solo"))
        clock["t"] = 3.0
        assert q.starvation_by_namespace()["solo"] == pytest.approx(3.0)


class TestShedRequeuePreservesEnqueuedAt:
    """The ISSUE 14 satellite extending the PR-12 conflict fix to 429s:
    a shed bind must requeue through the conflict-style backoff path with
    the ORIGINAL queue-admission instant, so the e2e histogram spans the
    whole shed-and-retry — never the error log, never a fresh clock."""

    def _scheduler(self):
        from kubernetes_tpu.core.scheduler import Scheduler
        from kubernetes_tpu.testing.wrappers import make_node
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n-0").capacity(
                {"cpu": 8, "memory": "32Gi", "pods": 110}).obj())
        return s

    def _popped(self, s, name="shed-victim"):
        from kubernetes_tpu.testing.wrappers import make_pod
        p = make_pod().name(name).req({"cpu": "100m"}).obj()
        s.queue.add(p)
        qpi = s.queue.pop()
        assert qpi.enqueued_at is not None
        s.queue.done(p.uid)
        return p, qpi

    def test_async_shed_requeues_with_original_stamp(self):
        s = self._scheduler()
        p, qpi = self._popped(s)
        orig = qpi.enqueued_at
        p.node_name = "n-0"
        s.cache.assume_pod(p, qpi.pod_info)

        class _E(Exception):
            code = 429

            def read(self):
                return b'{"error": "TooManyRequests"}'

        s.handle.on_async_bind_error(p, _E())
        assert s.shed_requeues == 1
        assert not s.error_log, s.error_log
        requeued = s.queue.backoff_q.get(p.uid) or s.queue.active_q.get(p.uid)
        assert requeued is not None
        assert requeued.enqueued_at == orig, (
            "shed requeue restarted the e2e clock")

    def test_sync_shed_status_routes_through_conflict_requeue(self):
        from kubernetes_tpu.core.framework import CycleState, Status
        s = self._scheduler()
        p, qpi = self._popped(s)
        orig = qpi.enqueued_at
        p.node_name = "n-0"
        s.cache.assume_pod(p, qpi.pod_info)
        st = Status.bind_shed("429 TooManyRequests")
        assert st.shed and not st.conflict
        fw = next(iter(s.profiles.values()))
        s._unwind_binding(fw, CycleState(), qpi, "n-0", st)
        assert s.shed_requeues == 1
        assert not s.error_log, s.error_log
        got = s.queue.backoff_q.get(p.uid) or s.queue.active_q.get(p.uid)
        assert got is qpi and got.enqueued_at == orig
