"""Fleet conductor (kubernetes_tpu/fleet/): the declarative many-process
cluster (ISSUE 19).

Units: FleetSpec roundtrip + validation; HollowProfile.split(n) is
disjoint-and-complete over the absolute index space (the name-prefix
ranges N hollow processes divide one profile by); the --name-prefix-range
CLI flag registers exactly its sub-range.

Integration (ONE amortized fleet: 1 leader + 1 follower + 2 shards + 2
hollow members over a 40-node split profile, short shard lease): staged
bring-up barriers, every pod bound exactly once, hollow kill9 → the
supervisor respawns the member with --adopt and its exact range recovers
with ZERO duplicate nodes, shard kill9 → left-to-adoption (the ring
successor adopts the lease; the conductor must NOT respawn — that would
race the adoption), the consolidated detail line, SIGUSR2 flight-record
fan-out. Then the ``python -m kubernetes_tpu.fleet`` entrypoint drives a
small fleet through the measured-pod path in-process.

Tests in the integration class are ORDERED (chaos builds on the smoke
state) — the module fixture is the amortization seam.
"""

import json
import signal
import sys
import time

import pytest

from kubernetes_tpu.fleet import DEFAULT_RESTART, FleetConductor, FleetSpec
from kubernetes_tpu.fleet.conductor import SIGUSR2_ROLES
from kubernetes_tpu.hollow import HollowProfile
from kubernetes_tpu.shard.harness import (_call, _env, _repo_root,
                                          scrape_metrics)


def _wait_true(cond, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# FleetSpec: roundtrip + validation
# ---------------------------------------------------------------------------


class TestFleetSpec:
    def test_roundtrip_and_load(self, tmp_path):
        spec = FleetSpec(name="rt", shards=3, shard_lease_s=4.0,
                         mesh_devices=8, replicas=2,
                         hollow={"count": 100, "zones": 4},
                         hollow_procs=4,
                         workload={"managers": 2},
                         env={"X": "1"}, shard_env={"Y": "2"},
                         restart=dict(DEFAULT_RESTART, hollow="never"))
        path = tmp_path / "fleet.json"
        path.write_text(json.dumps(spec.to_dict()))
        got = FleetSpec.load(str(path)).validate()
        assert got.to_dict() == spec.to_dict()
        assert got.shards == 3 and got.hollow_procs == 4
        assert got.restart["hollow"] == "never"
        # unspecified roles keep their defaults through the merge
        assert got.restart["shard"] == "adopt"
        assert got.restart["apiserver"] == "never"

    def test_from_dict_merges_restart_over_defaults(self):
        got = FleetSpec.from_dict({"restart": {"hollow": "never"}})
        assert got.restart["hollow"] == "never"
        assert got.restart["controller"] == "restart"

    @pytest.mark.parametrize("patch", [
        {"shards": 0},
        {"replicas": -1},
        {"hollow_procs": 0},
        {"mesh_devices": -2},
        {"max_restarts": -1},
        {"supervise_interval_s": 0.0},
        {"restart": {"hollow": "pray"}},
        {"hollow": {"count": 0}},
        {"hollow": {"count": 4}, "hollow_procs": 8},
        {"workload": {"managers": 0}},
    ])
    def test_validate_rejects(self, patch):
        base = {"hollow": {"count": 16}}
        base.update(patch)
        with pytest.raises(ValueError):
            FleetSpec.from_dict(base).validate()


# ---------------------------------------------------------------------------
# HollowProfile.split(n): disjoint and complete
# ---------------------------------------------------------------------------


class TestProfileSplit:
    @pytest.mark.parametrize("count,n", [
        (40, 2), (41, 3), (5, 5), (10, 1), (7, 16), (100, 8)])
    def test_split_is_disjoint_and_complete(self, count, n):
        prof = HollowProfile.from_dict(
            {"count": count, "zones": 4, "churn_per_s": 2.0})
        subs = prof.split(n)
        assert len(subs) == min(n, count)
        covered = []
        for sub in subs:
            assert sub.total == count          # absolute-space marker
            assert sub.count == len(sub.index_range())
            covered.extend(sub.index_range())
        # disjoint AND complete: the concatenated ranges ARE 0..count-1
        assert covered == list(range(count))
        # churn divides proportionally — the fleet's aggregate rate is
        # the profile's rate regardless of member count
        assert sum(s.churn_per_s for s in subs) == pytest.approx(2.0)

    def test_resplit_preserves_absolute_indices(self):
        prof = HollowProfile.from_dict({"count": 40, "zones": 4})
        right = prof.split(2)[1]            # offsets 20..39
        nested = right.split(2)
        assert [list(s.index_range()) for s in nested] == [
            list(range(20, 30)), list(range(30, 40))]
        assert all(s.total == 40 for s in nested)

    def test_name_prefix_range_flag_registers_exact_subrange(self, tmp_path):
        """--name-prefix-range START:END on the hollow CLI: the plane
        registers exactly nodes prefix-START..prefix-(END-1), announcing
        the sub-range count on its ready line."""
        from kubernetes_tpu.core.apiserver import APIServer
        from kubernetes_tpu.testing.faults import drain_pipe, spawn_ready

        api = APIServer()
        port = api.serve(0)
        base = f"http://127.0.0.1:{port}"
        prof = tmp_path / "prof.json"
        prof.write_text(json.dumps(
            {"count": 30, "name_prefix": "hx", "zones": 3,
             "heartbeat_s": 60.0}))
        proc = None
        try:
            proc, m = spawn_ready(
                [sys.executable, "-m", "kubernetes_tpu.hollow",
                 "--api-url", base, "--profile", str(prof),
                 "--name-prefix-range", "10:20"],
                r"registered (\d+) nodes", cwd=_repo_root(), env=_env(),
                timeout=120)
            drain_pipe(proc)
            assert int(m.group(1)) == 10
            from kubernetes_tpu.core.apiserver import fetch_paged
            names = {w["name"] for w in fetch_paged(base, "nodes")}
            assert names == {f"hx-{i}" for i in range(10, 20)}
        finally:
            if proc is not None:
                proc.terminate()
                proc.wait(timeout=15)
            api.shutdown()


# ---------------------------------------------------------------------------
# the amortized fleet: smoke + chaos + detail
# ---------------------------------------------------------------------------


N_NODES = 40
N_PODS = 60


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    flight = tmp_path_factory.mktemp("flightrec")
    spec = FleetSpec(
        name="t1-smoke", shards=2, shard_lease_s=2.0, replicas=1,
        hollow={"count": N_NODES, "zones": 4, "heartbeat_s": 30.0,
                "churn_per_s": 1.0, "churn_cordon_s": 0.2},
        hollow_procs=2, supervise_interval_s=0.25,
        flightrec_dir=str(flight), startup_timeout_s=300.0)
    conductor = FleetConductor(spec).start()
    yield conductor
    conductor.stop()


def _slot(name: str):
    """Absolute slot index of a hollow node name: 'hollow-7' and its
    replacement generations 'hollow-7r2' both map to 7."""
    tail = name.split("-", 1)[1]
    if tail.isdigit():
        return int(tail)
    slot, _, gen = tail.partition("r")
    return int(slot) if slot.isdigit() and gen.isdigit() else None


class TestFleetIntegration:
    def test_staged_bringup_barriers(self, fleet):
        assert [s["stage"] for s in fleet.stages] == [
            "leader", "followers", "shards", "hollow"]
        assert len(fleet.members_of("apiserver")) == 1
        assert len(fleet.members_of("follower")) == 1
        assert len(fleet.members_of("shard")) == 2
        assert len(fleet.members_of("hollow")) == 2
        assert all(m.alive() for m in fleet.members)
        # the hollow barrier: members acknowledged their EXACT sub-ranges
        assert [m.registered
                for m in fleet.members_of("hollow")] == [20, 20]
        # the shards-leased barrier held: every slot owned at stage exit
        owned = sum(scrape_metrics(u).get("scheduler_shard_owned_shards",
                                          0.0) for u in fleet.shard_urls)
        assert owned >= 2

    def test_all_pods_bind_exactly_once(self, fleet):
        from kubernetes_tpu.core.apiserver import fetch_paged, pod_to_wire
        from kubernetes_tpu.testing.wrappers import make_pod

        proto = make_pod().name("proto").req(
            {"cpu": "100m", "memory": "64Mi"}).labels(
            {"app": "fleet-smoke"}).obj()
        wires = [pod_to_wire(proto.clone_from_template(f"smoke-{i}"))
                 for i in range(N_PODS)]
        _call(fleet.base, "POST", "/api/v1/pods", wires, timeout=120)

        def bound():
            s = _call(fleet.base, "GET", "/api/v1/pods?summary=true")
            fleet.note_bound(int(s["bound"]))
            return s["bound"] >= N_PODS
        assert _wait_true(bound, timeout=120), "pods never all bound"
        # exactly-once: one store object per pod name, each bound once.
        # Paged sweep — a full-list GET would itself trip the unpaged
        # counter asserted below.
        pods = [w for w in fetch_paged(fleet.base, "pods")
                if w["name"].startswith("smoke-")]
        assert len(pods) == N_PODS
        assert len({w["name"] for w in pods}) == N_PODS
        assert all(w.get("nodeName") for w in pods)
        # the paged-plane contract holds on leader AND follower
        for url in [fleet.base] + fleet.follower_urls:
            m = scrape_metrics(url)
            assert m.get("apiserver_list_unpaged_total", 0.0) == 0.0, url
            assert m.get("apiserver_relisted_watches_total", 0.0) == 0.0, url

    def test_hollow_kill9_supervised_restart_same_range(self, fleet):
        victim = fleet.members_of("hollow")[1]
        victim.proc.send_signal(signal.SIGKILL)
        victim.proc.wait()
        assert _wait_true(lambda: victim.restarts >= 1 and victim.alive(),
                          timeout=90), "supervisor never respawned member"
        assert any(e["member"] == victim.name
                   and e["action"] == "restarted"
                   for e in fleet.events)
        time.sleep(2.0)  # churn keeps replacing nodes post-restart

        def census_whole():
            from kubernetes_tpu.core.apiserver import fetch_paged
            names = [w["name"]
                     for w in fetch_paged(fleet.base, "nodes")]
            slots = sorted(_slot(n) for n in names)
            return len(names) == N_NODES and slots == list(range(N_NODES))
        # zero duplicates, zero holes: the EXACT range recovered
        assert _wait_true(census_whole, timeout=60), \
            "hollow range did not recover exactly"

    def test_shard_kill9_left_to_adoption_not_respawned(self, fleet):
        victim = fleet.members_of("shard")[1]
        survivor = fleet.members_of("shard")[0]
        victim.proc.send_signal(signal.SIGKILL)
        victim.proc.wait()
        assert _wait_true(
            lambda: any(e["member"] == victim.name
                        and e["action"] == "left-to-adoption"
                        for e in fleet.events), timeout=60)
        # the conductor did NOT respawn (that would race lease adoption)
        assert victim.restarts == 0 and not victim.alive()
        # the ring successor adopts the dead shard's slot (2s lease)
        assert _wait_true(
            lambda: scrape_metrics(survivor.url).get(
                "scheduler_shard_owned_shards", 0.0) >= 2, timeout=60), \
            "survivor never adopted the dead shard's lease"
        # and the plane still binds: exactly-once holds across the loss
        from kubernetes_tpu.core.apiserver import fetch_paged, pod_to_wire
        from kubernetes_tpu.testing.wrappers import make_pod
        proto = make_pod().name("proto2").req(
            {"cpu": "100m", "memory": "64Mi"}).labels(
            {"app": "post-adopt"}).obj()
        _call(fleet.base, "POST", "/api/v1/pods",
              [pod_to_wire(proto.clone_from_template(f"adopt-{i}"))
               for i in range(20)], timeout=120)

        def adopted_bound():
            pods = [w for w in fetch_paged(fleet.base, "pods")
                    if w["name"].startswith("adopt-")]
            return (len(pods) == 20
                    and all(w.get("nodeName") for w in pods))
        assert _wait_true(adopted_bound, timeout=120)

    def test_consolidated_detail_schema(self, fleet):
        d = fleet.detail()
        assert d["name"] == "t1-smoke"
        assert [s["stage"] for s in d["stages"]] == [
            "leader", "followers", "shards", "hollow"]
        assert all(set(s) == {"stage", "elapsed_s", "members"}
                   for s in d["stages"])
        for m in d["members"]:
            assert {"name", "role", "index", "pid", "alive", "url",
                    "restarts", "rss_peak_mb"} <= set(m)
        rss = d["rss_mb"]
        assert rss["apiserver"] > 0
        assert len(rss["shards"]) == 2 and len(rss["followers"]) == 1
        assert rss["hollow"] > 0 and len(rss["hollow_members"]) == 2
        # the supervision ledger is consolidated, never silent
        assert d["restarts"] >= 1
        actions = {e["action"] for e in d["events"]}
        assert {"restarted", "left-to-adoption"} <= actions
        # throughput window from the bind test's note_bound samples
        assert d["throughput"] is not None
        assert d["throughput"]["bound"] >= N_PODS
        assert isinstance(d["flightrec_artifacts"], int)

    def test_sigusr2_fanout_hits_handler_roles_only(self, fleet):
        live_targets = [m for m in fleet.members
                        if m.role in SIGUSR2_ROLES and m.alive()]
        assert fleet.signal_flightrec() == len(live_targets)
        # flight records actually land (apiserver/follower/shard dumps)
        assert _wait_true(lambda: len(fleet.artifacts()) >= 1, timeout=30)
        time.sleep(0.3)
        assert all(m.alive() for m in live_targets), \
            "SIGUSR2 killed a member that should have a handler"


# ---------------------------------------------------------------------------
# the entrypoint: python -m kubernetes_tpu.fleet --spec ... --pods N
# ---------------------------------------------------------------------------


def test_fleet_entrypoint_drives_measured_pods(tmp_path, capsys):
    from kubernetes_tpu.fleet.__main__ import main

    spec = FleetSpec(
        name="entry", shards=1,
        hollow={"count": 24, "zones": 4, "heartbeat_s": 30.0},
        startup_timeout_s=300.0)
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(spec.to_dict()))
    rc = main(["--spec", str(path), "--pods", "24", "--warm", "8",
               "--timeout", "600"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["all_bound"] is True
    assert out["distinct_bound_pods"] == 24 + 8
    # the consolidated fleet detail rides the result line
    assert out["fleet"]["name"] == "entry"
    assert [s["stage"] for s in out["fleet"]["stages"]] == [
        "leader", "shards", "hollow"]
