"""Node-lifecycle controller (kubernetes_tpu/controllers/): heartbeat
health, the taint ladder, rate-limited zone-aware eviction, idempotent
eviction intents, and the closed loop against the real apiserver
(docs/RESILIENCE.md § node lifecycle)."""

import copy
import json
import threading
import time
from urllib import request as urlrequest
from urllib.error import HTTPError

import pytest

from kubernetes_tpu.controllers import (NodeLifecycleController,
                                        RateLimitedEvictor, TokenBucket)
from kubernetes_tpu.controllers.evictor import (GC_ZONE, ZONE_FULL,
                                                ZONE_NORMAL, ZONE_PARTIAL,
                                                intent_for)
from kubernetes_tpu.controllers.node_lifecycle import UNKNOWN
from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import (EVICTED_ANNOTATION,
                                           UNREACHABLE_TAINT, APIServer,
                                           HTTPClientset, node_to_wire,
                                           pod_to_wire)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _call(base, method, path, body=None, timeout=30.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urlrequest.Request(base + path, data=data, method=method,
                            headers={"Content-Type": "application/json"})
    with urlrequest.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# TokenBucket units (injected clock: no sleeps)
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_rate(self):
        clock = [0.0]
        b = TokenBucket(qps=2.0, burst=2.0, now=lambda: clock[0])
        assert b.try_take() and b.try_take()   # burst balance
        assert not b.try_take()                # dry until refill
        clock[0] = 0.5                         # 0.5s * 2qps = 1 token
        assert b.try_take()
        assert not b.try_take()

    def test_balance_capped_at_burst(self):
        clock = [0.0]
        b = TokenBucket(qps=10.0, burst=1.0, now=lambda: clock[0])
        assert b.try_take()
        clock[0] = 100.0                       # huge idle stretch
        assert b.try_take()
        assert not b.try_take()                # capped at burst=1, not 1000

    def test_zero_qps_never_grants(self):
        clock = [0.0]
        b = TokenBucket(qps=0.0, burst=4.0, now=lambda: clock[0])
        assert not b.try_take()                # full-disruption bucket
        clock[0] = 1e6
        assert not b.try_take()

    def test_set_rate_keeps_accumulated_balance(self):
        clock = [0.0]
        b = TokenBucket(qps=1.0, burst=2.0, now=lambda: clock[0])
        clock[0] = 1.0                         # balance pinned at burst
        b.set_rate(0.0)                        # zone went FullDisruption
        assert not b.try_take()                # zero-rate wins immediately
        b.set_rate(1.0)
        clock[0] = 2.5
        assert b.try_take()                    # refills resume on recovery


# ---------------------------------------------------------------------------
# RateLimitedEvictor units (stub clientset, injected clock)
# ---------------------------------------------------------------------------


class _StubClientset:
    """Programmable eviction endpoint: records calls, mimics the server's
    ledger/404 answers without a socket."""

    def __init__(self):
        self.calls = []
        self.ledger = {}
        self.gone = set()
        self.fail_transport = 0   # next N calls die before reaching "the wire"

    def evict_pod(self, uid, node, intent):
        self.calls.append((uid, node, intent))
        if self.fail_transport > 0:
            self.fail_transport -= 1
            raise OSError("connection refused")
        if uid in self.gone:
            raise HTTPError("http://stub", 404, "pod not found", None, None)
        if self.ledger.get(uid) == intent:
            return {"evicted": True, "already": True}
        self.ledger[uid] = intent
        return {"evicted": True, "node": node}


class TestRateLimitedEvictor:
    def _evictor(self, **kw):
        clock = [0.0]
        cs = _StubClientset()
        ev = RateLimitedEvictor(cs, now=lambda: clock[0], **kw)
        return ev, cs, clock

    def test_zone_state_machine(self):
        ev, _cs, _clock = self._evictor(primary_qps=4.0, secondary_qps=0.5,
                                        unhealthy_threshold=0.5)
        assert ev.set_zone_state("a", 0, 10) == ZONE_NORMAL
        assert ev.set_zone_state("a", 6, 10) == ZONE_PARTIAL
        assert ev.set_zone_state("a", 10, 10) == ZONE_FULL
        assert ev._buckets["a"].qps == 0.0
        assert ev.set_zone_state("a", 1, 10) == ZONE_NORMAL
        assert ev._buckets["a"].qps == 4.0

    def test_enqueue_dedupes_by_uid(self):
        ev, _cs, _clock = self._evictor()
        assert ev.enqueue("a", "n1", "u1")
        assert not ev.enqueue("a", "n1", "u1")  # reconcile re-plans
        assert ev.pending_count() == 1

    def test_throttle_counts_and_resumes(self):
        ev, cs, clock = self._evictor(primary_qps=1.0, burst=1.0)
        ev.set_zone_state("a", 0, 10)
        for i in range(3):
            ev.enqueue("a", "n1", f"u{i}")
        assert ev.run_once() == 1              # burst grants exactly one
        assert ev.evictions_throttled_total == 1
        assert ev.pending_count() == 2
        clock[0] = 10.0                        # refill (capped at burst)
        assert ev.run_once() == 1
        assert len(cs.calls) == 2

    def test_full_disruption_zone_evicts_nothing(self):
        ev, cs, clock = self._evictor(primary_qps=100.0, burst=10.0)
        ev.set_zone_state("dead", 10, 10)      # FULL: qps=0
        ev.enqueue("dead", "n1", "u1")
        clock[0] = 1e6
        assert ev.run_once() == 0
        assert cs.calls == []
        assert ev.evictions_throttled_total >= 1

    def test_cancel_node_drops_pending(self):
        ev, cs, _clock = self._evictor(primary_qps=100.0, burst=10.0)
        ev.enqueue("a", "n1", "u1")
        ev.enqueue("a", "n2", "u2")
        assert ev.cancel_node("n1") == 1       # taint lifted mid-wave
        assert ev.evictions_cancelled == 1
        ev.run_once()
        assert [c[0] for c in cs.calls] == ["u2"]  # n1's pod kept placement
        # a cancelled uid may be re-planned later (node died again)
        assert ev.enqueue("a", "n1", "u1")

    def test_restart_replay_is_exactly_once(self):
        """A restarted controller re-plans the same wave: deterministic
        intent ids make the server's ledger answer already=True — counted
        as replayed, never as a second eviction."""
        ev1, cs, _clock = self._evictor(primary_qps=100.0, burst=10.0)
        ev1.enqueue("a", "n1", "u1")
        assert ev1.run_once() == 1
        # fresh evictor (controller restart), same clientset/ledger
        ev2 = RateLimitedEvictor(cs, primary_qps=100.0, burst=10.0,
                                 now=lambda: 0.0)
        ev2.enqueue("a", "n1", "u1")
        assert ev2.run_once() == 0
        assert ev2.evictions_replayed == 1 and ev2.evictions_total == 0
        assert [c[2] for c in cs.calls] == [intent_for("u1", "n1")] * 2

    def test_pod_gone_404_cancels(self):
        ev, cs, _clock = self._evictor(primary_qps=100.0, burst=10.0)
        cs.gone.add("u1")
        ev.enqueue("a", "n1", "u1")
        assert ev.run_once() == 0
        assert ev.evictions_cancelled == 1 and ev.eviction_errors == 0

    def test_transport_retry_requeues_into_original_zone(self):
        """A transport failure re-queues the pod into its ORIGINAL zone,
        so the retry still pays that zone's (possibly disrupted) rate —
        a zone-less retry would drain at primary QPS, bypassing the very
        brake the disruption state machine exists to apply."""
        ev, cs, clock = self._evictor(primary_qps=100.0, burst=10.0)
        ev.set_zone_state("z", 0, 10)          # Normal while planned
        ev.enqueue("z", "n1", "u1")
        cs.fail_transport = 1
        assert ev.run_once() == 0              # token spent, wire died
        assert ev.eviction_errors == 1
        assert ev._queued["u1"] == ("z", "n1")
        # the zone collapses before the retry: its brake must govern it
        ev.set_zone_state("z", 10, 10)
        clock[0] = 1e6
        assert ev.run_once() == 0
        assert ev.evictions_throttled_total >= 1
        assert len(cs.calls) == 1              # the retry never fired

    def test_gc_zone_is_census_proof(self):
        """The reserved GC key is not a zone: a census naming it (which a
        real fleet cannot produce — "/" is illegal in a zone label value)
        must not re-rate the always-primary GC funnel."""
        ev, cs, _clock = self._evictor(primary_qps=100.0, burst=10.0)
        assert ev.set_zone_state(GC_ZONE, 10, 10) == ZONE_NORMAL
        ev.enqueue(GC_ZONE, "vanished-node", "u1")
        assert ev.run_once() == 1
        assert [c[0] for c in cs.calls] == ["u1"]


# ---------------------------------------------------------------------------
# Taint ladder + GC units (FakeClientset-backed, injected clock + ages)
# ---------------------------------------------------------------------------


class _LadderClientset(FakeClientset):
    """FakeClientset + an in-memory eviction subresource mirroring the
    server's semantics (ledger, unbind, pending recreate)."""

    def __init__(self):
        super().__init__()
        self.ledger = {}
        self.evicted_uids = []

    def evict_pod(self, uid, node, intent):
        if self.ledger.get(uid) == intent:
            return {"evicted": True, "already": True}
        pod = self.pods.get(uid)
        if pod is None:
            raise HTTPError("http://fake", 404, "pod not found", None, None)
        if not pod.node_name:
            return {"evicted": False, "pending": True}
        self.delete_pod(pod)
        recreated = copy.deepcopy(pod)
        recreated.node_name = ""
        recreated.annotations = dict(recreated.annotations,
                                     **{EVICTED_ANNOTATION: intent})
        self.create_pod(recreated)
        self.ledger[uid] = intent
        self.evicted_uids.append(uid)
        return {"evicted": True, "node": node}


def _ladder(grace=5.0, noexec_after=3.0, **ev_kw):
    clock = [0.0]
    ages = {}
    cs = _LadderClientset()
    ctrl = NodeLifecycleController(
        cs, grace=grace, noexec_after=noexec_after,
        ages_fn=lambda: dict(ages), now=lambda: clock[0], **ev_kw)
    return ctrl, cs, clock, ages


class TestTaintLadder:
    def _cluster(self, cs):
        for i in range(3):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "16Gi",
                                      "pods": 110})
                           .zone("z0").obj())
        pods = []
        for i in range(2):
            p = make_pod().name(f"p{i}").req({"cpu": "100m"}).obj()
            p.node_name = "n1"
            cs.create_pod(p)
            pods.append(p)
        return pods

    def test_ladder_climbs_noschedule_then_noexecute(self):
        ctrl, cs, clock, ages = _ladder(primary_qps=100.0,
                                        eviction_burst=10.0)
        pods = self._cluster(cs)
        ages.update({"n0": 0.0, "n1": 0.0, "n2": 0.0})
        ctrl.reconcile_once()
        assert ctrl.node_health == {"n0": "Ready", "n1": "Ready",
                                    "n2": "Ready"}
        assert all(not n.taints for n in cs.nodes.values())
        # n1 goes silent past grace: Unknown + NoSchedule, nothing evicted
        ages["n1"] = 6.0
        ctrl.reconcile_once()
        assert ctrl.node_health["n1"] == UNKNOWN
        effects = {t.effect for t in cs.nodes["n1"].taints
                   if t.key == UNREACHABLE_TAINT}
        assert effects == {"NoSchedule"}
        assert ctrl.taints_noschedule == 1 and cs.evicted_uids == []
        # still silent but inside the tolerance window: idempotent (no
        # double-taint — the settled ladder step must not re-PUT)
        ctrl.reconcile_once()
        assert ctrl.taints_noschedule == 1 and ctrl.taint_errors == 0
        # tolerance expires: NoExecute lands and the bound pods drain
        clock[0] = 4.0
        ages["n1"] = 10.0
        ctrl.reconcile_once()
        effects = {t.effect for t in cs.nodes["n1"].taints
                   if t.key == UNREACHABLE_TAINT}
        assert effects == {"NoSchedule", "NoExecute"}
        assert ctrl.taints_noexecute == 1
        assert sorted(cs.evicted_uids) == sorted(p.uid for p in pods)
        # evicted pods were recreated pending with the intent annotation
        for p in pods:
            got = cs.pods[p.uid]
            assert got.node_name == ""
            assert got.annotations[EVICTED_ANNOTATION] == intent_for(
                p.uid, "n1")

    def test_heartbeat_return_lifts_taints_and_cancels_wave(self):
        # burst=1: one eviction per pass, the rest stay pending
        ctrl, cs, clock, ages = _ladder(primary_qps=1e-9,
                                        eviction_burst=1.0)
        pods = self._cluster(cs)
        ages.update({"n0": 0.0, "n1": 20.0, "n2": 0.0})
        ctrl.reconcile_once()                  # NoSchedule
        clock[0] = 4.0
        ctrl.reconcile_once()                  # NoExecute + 1 eviction
        assert len(cs.evicted_uids) == 1
        assert ctrl.evictor.pending_count() == 1
        # n1 heartbeats again: taints lift, the pending eviction cancels
        ages["n1"] = 0.0
        ctrl.reconcile_once()
        assert ctrl.taints_lifted == 1
        assert cs.nodes["n1"].taints == []
        assert ctrl.evictor.pending_count() == 0
        assert ctrl.evictor.evictions_cancelled >= 1
        # the survivor kept its placement
        survivors = [p for p in pods if p.uid not in cs.evicted_uids]
        assert len(survivors) == 1
        assert cs.pods[survivors[0].uid].node_name == "n1"

    def test_pod_gc_reaps_deleted_node_pods(self):
        ctrl, cs, _clock, ages = _ladder(primary_qps=100.0,
                                         eviction_burst=10.0)
        self._cluster(cs)
        ghost = make_pod().name("ghost").req({"cpu": "100m"}).obj()
        ghost.node_name = "vanished-node"
        cs.create_pod(ghost)
        ages.update({"n0": 0.0, "n1": 0.0, "n2": 0.0})
        ctrl.reconcile_once()
        assert ctrl.pods_gc == 1
        assert cs.pods[ghost.uid].node_name == ""
        assert EVICTED_ANNOTATION in cs.pods[ghost.uid].annotations

    def test_unlabeled_zone_outage_does_not_stall_gc(self):
        """Nodes missing the zone label census under zone "" — a REAL
        zone whose disruption brake applies to ITS evictions only:
        deleted-node pod GC drains through the reserved GC_ZONE queue and
        must keep moving even while the unlabeled zone is frozen."""
        ctrl, cs, clock, ages = _ladder(primary_qps=100.0,
                                        eviction_burst=10.0)
        for i in range(2):   # no .zone(): census zone is ""
            cs.create_node(make_node().name(f"u{i}")
                           .capacity({"cpu": 8, "memory": "16Gi",
                                      "pods": 110}).obj())
        ghost = make_pod().name("ghost").req({"cpu": "100m"}).obj()
        ghost.node_name = "vanished-node"
        cs.create_pod(ghost)
        ages.update({"u0": 99.0, "u1": 99.0})   # the whole "" zone silent
        ctrl.reconcile_once()
        clock[0] = 10.0
        ctrl.reconcile_once()
        assert ctrl.evictor.zone_states[""] == ZONE_FULL
        assert ctrl.pods_gc == 1
        assert cs.pods[ghost.uid].node_name == ""   # GC drained anyway

    def test_zone_census_throttles_before_evicting(self):
        """A fully-silent zone must never storm: every one of its nodes is
        Unknown, so its bucket is zero-rate BEFORE any eviction token is
        taken this pass."""
        ctrl, cs, clock, ages = _ladder(primary_qps=100.0,
                                        eviction_burst=10.0,
                                        unhealthy_threshold=0.55)
        self._cluster(cs)                      # all three nodes in z0
        ages.update({"n0": 20.0, "n1": 20.0, "n2": 20.0})
        ctrl.reconcile_once()
        clock[0] = 10.0
        ctrl.reconcile_once()                  # NoExecute everywhere
        assert ctrl.evictor.zone_states["z0"] == ZONE_FULL
        assert cs.evicted_uids == []           # zero evictions: outage
        assert ctrl.evictor.evictions_throttled_total >= 1
        s = ctrl.stats()
        assert s["nodes_unknown"] == 3 and s["evictions"] == 0

    def test_metrics_text_exposes_series(self):
        ctrl, cs, _clock, ages = _ladder()
        self._cluster(cs)
        ages.update({"n0": 0.0, "n1": 0.0, "n2": 0.0})
        ctrl.reconcile_once()
        text = ctrl.metrics_text()
        for series in ("node_lifecycle_evictions_total",
                       "node_lifecycle_evictions_throttled_total",
                       "node_lifecycle_reconciles_total",
                       "node_lifecycle_nodes_unknown",
                       'node_lifecycle_zone_state{zone="z0"}'):
            assert series in text, series


# ---------------------------------------------------------------------------
# Eviction subresource semantics (real apiserver over the wire)
# ---------------------------------------------------------------------------


@pytest.fixture()
def api():
    server = APIServer()
    port = server.serve(0)
    yield server, f"http://127.0.0.1:{port}"
    server.shutdown()


class TestEvictionSubresource:
    def _bound_pod(self, base, name="victim", node="n0"):
        _call(base, "POST", "/api/v1/nodes",
              node_to_wire(make_node().name(node)
                           .capacity({"cpu": 8, "pods": 110}).obj()))
        p = make_pod().name(name).req({"cpu": "100m"}).obj()
        w = pod_to_wire(p)
        w["nodeName"] = node
        _call(base, "POST", "/api/v1/pods", w)
        return w["uid"]

    def test_evict_unbinds_and_recreates_pending(self, api):
        server, base = api
        uid = self._bound_pod(base)
        intent = intent_for(uid, "n0")
        got = _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                    {"intent": intent, "node": "n0"})
        assert got == {"evicted": True, "node": "n0"}
        pod = server.store.pods[uid]
        assert pod.node_name == ""
        assert pod.annotations[EVICTED_ANNOTATION] == intent
        assert server.pod_evictions == 1
        assert server.evictions[uid] == intent

    def test_replay_answers_already_without_mutating(self, api):
        server, base = api
        uid = self._bound_pod(base)
        intent = intent_for(uid, "n0")
        _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
              {"intent": intent, "node": "n0"})
        got = _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                    {"intent": intent, "node": "n0"})
        assert got.get("already") is True
        assert server.pod_evictions == 1           # no second mutation
        assert server.pod_evictions_replayed == 1

    def test_missing_intent_is_400(self, api):
        _server, base = api
        uid = self._bound_pod(base)
        with pytest.raises(HTTPError) as e:
            _call(base, "POST", f"/api/v1/pods/{uid}/eviction", {})
        assert e.value.code == 400

    def test_unknown_pod_is_404(self, api):
        _server, base = api
        with pytest.raises(HTTPError) as e:
            _call(base, "POST", "/api/v1/pods/nope/eviction",
                  {"intent": "i", "node": "n0"})
        assert e.value.code == 404

    def test_node_mismatch_is_409(self, api):
        """The pod moved since the controller planned the wave: the stale
        plan must NOT evict it off its new home."""
        _server, base = api
        uid = self._bound_pod(base)
        with pytest.raises(HTTPError) as e:
            _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                  {"intent": intent_for(uid, "other"), "node": "other"})
        assert e.value.code == 409

    def test_unbound_pod_answers_pending(self, api):
        _server, base = api
        p = make_pod().name("loose").req({"cpu": "100m"}).obj()
        w = pod_to_wire(p)
        _call(base, "POST", "/api/v1/pods", w)
        got = _call(base, "POST", f"/api/v1/pods/{w['uid']}/eviction",
                    {"intent": "i", "node": "n0"})
        assert got == {"evicted": False, "pending": True}

    def test_rebind_reopens_eviction_window(self, api):
        """Taint lifts, the pod re-binds to the SAME once-failed node,
        the node fails AGAIN: the re-bind pruned the ledger entry, so the
        re-minted uid@node intent is a fresh wave — not swallowed by a
        stale already=True that would pin the pod to a dead node."""
        server, base = api
        uid = self._bound_pod(base)
        intent = intent_for(uid, "n0")
        got = _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                    {"intent": intent, "node": "n0"})
        assert got["evicted"] is True
        assert server.evictions[uid] == intent
        _call(base, "POST", f"/api/v1/pods/{uid}/binding", {"node": "n0"})
        assert uid not in server.evictions     # window closed on re-bind
        got = _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                    {"intent": intent, "node": "n0"})
        assert got["evicted"] is True and "already" not in got
        assert server.pod_evictions == 2
        assert server.pod_evictions_replayed == 0
        assert server.store.pods[uid].node_name == ""

    def test_delete_prunes_ledger(self, api):
        """A gone pod needs no replay protection: its ledger entry must
        not outlive it (unbounded ledger/snapshot growth otherwise)."""
        server, base = api
        uid = self._bound_pod(base)
        _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
              {"intent": intent_for(uid, "n0"), "node": "n0"})
        assert uid in server.evictions
        _call(base, "DELETE", f"/api/v1/pods/{uid}")
        assert uid not in server.evictions

    def test_ledger_prune_survives_restart(self, api, tmp_path):
        """The prune is derived from the pod's own WAL'd BOUND record, so
        recovery replays evict-then-rebind to an EMPTY entry: a
        post-restart wave for the re-failed node evicts instead of
        replaying."""
        data = str(tmp_path / "state")
        server = APIServer(data_dir=data)
        port = server.serve(0)
        base = f"http://127.0.0.1:{port}"
        try:
            uid = self._bound_pod(base)
            intent = intent_for(uid, "n0")
            _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                  {"intent": intent, "node": "n0"})
            _call(base, "POST", f"/api/v1/pods/{uid}/binding",
                  {"node": "n0"})
        finally:
            server.shutdown()
        server2 = APIServer(data_dir=data)
        port2 = server2.serve(0)
        base2 = f"http://127.0.0.1:{port2}"
        try:
            assert uid not in server2.evictions
            got = _call(base2, "POST", f"/api/v1/pods/{uid}/eviction",
                        {"intent": intent, "node": "n0"})
            assert got["evicted"] is True and "already" not in got
            assert server2.pod_evictions == 1
        finally:
            server2.shutdown()

    def test_ledger_survives_restart(self, api, tmp_path):
        """Controller restart AND apiserver restart: the eviction ledger
        rides the WAL, so a replayed intent stays exactly-once across
        both."""
        data = str(tmp_path / "state")
        server = APIServer(data_dir=data)
        port = server.serve(0)
        base = f"http://127.0.0.1:{port}"
        try:
            uid = self._bound_pod(base)
            intent = intent_for(uid, "n0")
            got = _call(base, "POST", f"/api/v1/pods/{uid}/eviction",
                        {"intent": intent, "node": "n0"})
            assert got["evicted"] is True
        finally:
            server.shutdown()
        server2 = APIServer(data_dir=data)
        port2 = server2.serve(0)
        base2 = f"http://127.0.0.1:{port2}"
        try:
            assert server2.evictions[uid] == intent   # recovered from WAL
            pod = server2.store.pods[uid]
            assert pod.node_name == ""                # recreate recovered
            assert pod.annotations[EVICTED_ANNOTATION] == intent
            got = _call(base2, "POST", f"/api/v1/pods/{uid}/eviction",
                        {"intent": intent, "node": "n0"})
            assert got.get("already") is True
            assert server2.pod_evictions == 0         # replay, not mutation
        finally:
            server2.shutdown()


class TestHeartbeatAges:
    def test_ages_track_the_status_sink(self, api):
        server, base = api
        _call(base, "POST", "/api/v1/nodes",
              node_to_wire(make_node().name("hb0")
                           .capacity({"cpu": 4, "pods": 10}).obj()))
        ages = _call(base, "GET", "/api/v1/nodes/heartbeats")["ages"]
        assert "hb0" in ages and ages["hb0"] < 1.0   # create stamps
        time.sleep(0.15)
        aged = _call(base, "GET", "/api/v1/nodes/heartbeats")["ages"]["hb0"]
        assert aged >= 0.1
        _call(base, "POST", "/api/v1/nodes/status", {"names": ["hb0"]})
        fresh = _call(base, "GET", "/api/v1/nodes/heartbeats")["ages"]["hb0"]
        assert fresh < aged
        _call(base, "DELETE", "/api/v1/nodes/hb0")
        assert "hb0" not in _call(base, "GET",
                                  "/api/v1/nodes/heartbeats")["ages"]

    def test_clientset_ages_verb(self, api):
        _server, base = api
        cs = HTTPClientset(base)
        try:
            cs.create_node(make_node().name("hb1")
                           .capacity({"cpu": 4, "pods": 10}).obj())
            ages = cs.node_heartbeat_ages()
            assert "hb1" in ages
        finally:
            cs.close()


# ---------------------------------------------------------------------------
# Scheduler requeue accounting: replay-proof, re-eviction-aware
# ---------------------------------------------------------------------------


class TestSchedulerEvictionRequeueDedup:
    def test_relist_replay_counts_once_and_rebind_reopens(self):
        """The eviction annotation stays on the recreated pod, so a watch
        Replace (apiserver failover re-list) replays the same pending pod
        as a fresh ADDED — that replay must not re-count. But once the
        pod is observed bound, the residue dies (mirroring the server's
        ledger prune): a later eviction re-minting the SAME uid@node
        intent is a new wave and counts again."""
        cs = FakeClientset()
        sched = Scheduler(clientset=cs, deterministic_ties=True)
        p = make_pod().name("victim").req({"cpu": "100m"}).obj()
        intent = intent_for(p.uid, "n1")
        p.annotations[EVICTED_ANNOTATION] = intent
        sched._on_pod_event("add", None, p)
        assert sched.eviction_requeues == 1
        # failover re-list replays the identical pending pod
        sched._on_pod_event("add", None, p)
        assert sched.eviction_requeues == 1    # replay, not a new eviction
        # the pod re-binds; node n1 later fails again -> same intent id
        bound = copy.deepcopy(p)
        bound.node_name = "n1"
        sched._on_pod_event("update", p, bound)
        sched._on_pod_event("delete", bound, bound)   # eviction's DELETE
        recreated = copy.deepcopy(p)
        recreated.node_name = ""
        sched._on_pod_event("add", None, recreated)   # ...and recreate
        assert sched.eviction_requeues == 2    # a genuinely new wave


# ---------------------------------------------------------------------------
# Closed loop: hollow-style silence -> taint -> evict -> reschedule
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_closed_loop_silence_taint_evict_reschedule(api):
    """The standing loop in one process: nodes heartbeat except one; the
    controller declares it Unknown, climbs the taint ladder, drains its
    pods through the rate-limited evictor; the scheduler re-places every
    victim elsewhere exactly once; the heartbeat's return lifts the
    taints."""
    server, base = api
    cs = HTTPClientset(base)
    ctrl_cs = HTTPClientset(base)
    sched = Scheduler(clientset=cs, deterministic_ties=True)
    errors = []
    stop = threading.Event()

    def drive():
        while not stop.is_set():
            try:
                if not sched.run_until_idle():
                    time.sleep(0.01)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    ctrl = NodeLifecycleController(
        ctrl_cs, grace=1.0, noexec_after=0.4, tick=0.1,
        primary_qps=200.0, eviction_burst=32.0)
    hb_stop = threading.Event()

    def heartbeat():
        while not hb_stop.is_set():
            _call(base, "POST", "/api/v1/nodes/status",
                  {"names": ["n0", "n1", "n2"]})   # n3 is silent
            hb_stop.wait(0.2)

    hb = threading.Thread(target=heartbeat, daemon=True)
    try:
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "32Gi",
                                      "pods": 110})
                           .zone(f"z{i % 2}").obj())
        pods = [make_pod().name(f"p{i}").req({"cpu": "100m",
                                              "memory": "64Mi"}).obj()
                for i in range(24)]
        for p in pods:
            cs.create_pod(p)
        _wait(lambda: len(server.store.bindings) == 24, msg="initial binds")
        initial = dict(server.store.bindings)       # uid -> node
        victims = sorted(u for u, n in initial.items() if n == "n3")
        assert victims, "spread placement put nothing on n3?"
        hb.start()
        ctrl.start()
        # ladder: n3 -> Unknown -> NoSchedule+NoExecute, victims drain
        _wait(lambda: server.pod_evictions >= len(victims),
              msg="eviction wave")
        # every victim re-placed, off n3, exactly once
        _wait(lambda: all(server.store.bindings.get(u, "n3") != "n3"
                          for u in victims), msg="re-placement")
        final = dict(server.store.bindings)
        assert len(final) == 24
        for uid in victims:
            assert final[uid] != "n3", (uid, final[uid])
        # survivors untouched: zero spurious evictions
        for uid, node in initial.items():
            if uid not in victims:
                assert final[uid] == node
        # exactly-once bookkeeping end to end: one server mutation and one
        # scheduler requeue per victim — and every re-bind closed its
        # evicted-pending window, so the ledger drained back to empty
        # (bounded: no entry outlives the pod's pending window)
        assert server.pod_evictions == len(victims)
        assert sched.eviction_requeues == len(victims)
        assert len(server.evictions) == 0
        assert ctrl.evictor.evictions_total == len(victims)
        # heartbeats return: the ladder unwinds
        hb_stop.set()
        hb.join(timeout=5)
        _call(base, "POST", "/api/v1/nodes/status",
              {"names": ["n0", "n1", "n2", "n3"]})
        _wait(lambda: ctrl.taints_lifted >= 1, msg="taint lift")
        _wait(lambda: not server.store.nodes["n3"].taints, msg="clean node")
        assert not errors, errors
    finally:
        stop.set()
        hb_stop.set()
        ctrl.stop()
        t.join(timeout=10)
        cs.close()
        ctrl_cs.close()
