"""End-to-end host-path scheduler tests (the schedule_one_test.go layer)."""

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler, fit_only_profiles
from kubernetes_tpu.testing import make_node, make_pod


def new_scheduler(profiles=None, **kw):
    cs = FakeClientset()
    sched = Scheduler(clientset=cs, profile_factory=profiles, **kw)
    return cs, sched


class TestBasicScheduling:
    def test_single_pod_binds(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        pod = make_pod().name("p1").req({"cpu": "1"}).obj()
        cs.create_pod(pod)
        assert sched.schedule_one()
        assert cs.bindings[pod.uid] == "n1"
        assert sched.scheduled == 1

    def test_pod_prefers_emptier_node(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("big").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
        cs.create_node(make_node().name("small").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
        # load the small node
        filler = make_pod().name("filler").req({"cpu": "1500m"}).node("small").obj()
        cs.create_pod(filler)
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "big"

    def test_no_fit_goes_unschedulable(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "1", "memory": "1Gi", "pods": 10}).obj())
        pod = make_pod().name("huge").req({"cpu": "64"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert pod.uid not in cs.bindings
        assert len(sched.queue.unschedulable) == 1

    def test_unschedulable_requeued_on_node_add(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "1", "memory": "1Gi", "pods": 10}).obj())
        pod = make_pod().name("p").req({"cpu": "4"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert len(sched.queue.unschedulable) == 1
        cs.create_node(make_node().name("n2").capacity({"cpu": "8", "memory": "8Gi", "pods": 10}).obj())
        assert len(sched.queue.unschedulable) == 0  # moved by Node/Add event
        sched.run_until_idle()
        assert cs.bindings[pod.uid] == "n2"

    def test_many_pods_fill_cluster(self):
        cs, sched = new_scheduler()
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 110}).obj())
        pods = [make_pod().name(f"p{i}").req({"cpu": "500m"}).obj() for i in range(20)]
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        assert sched.scheduled == 20
        # resource accounting: each node has at most 8 pods (4 cpu / 500m)
        per_node = {}
        for uid, n in cs.bindings.items():
            per_node[n] = per_node.get(n, 0) + 1
        assert all(v <= 8 for v in per_node.values())
        assert sum(per_node.values()) == 20

    def test_priority_order(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "1", "memory": "8Gi", "pods": 10}).obj())
        low = make_pod().name("low").priority(1).req({"cpu": "800m"}).obj()
        high = make_pod().name("high").priority(100).req({"cpu": "800m"}).obj()
        cs.create_pod(low)
        cs.create_pod(high)
        sched.schedule_one()  # must pick high first
        assert high.uid in cs.bindings
        assert low.uid not in cs.bindings


class TestPlugins:
    def test_taints_block(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("tainted").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .taint("dedicated", "gpu", "NoSchedule").obj())
        cs.create_node(make_node().name("clean").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "clean"

    def test_toleration_allows(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("tainted").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .taint("dedicated", "gpu", "NoSchedule").obj())
        pod = (make_pod().name("p").req({"cpu": "1"})
               .toleration("dedicated", "gpu", "Equal", "NoSchedule").obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "tainted"

    def test_node_selector(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("a").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .label("disk", "hdd").obj())
        cs.create_node(make_node().name("b").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .label("disk", "ssd").obj())
        pod = make_pod().name("p").req({"cpu": "1"}).node_selector({"disk": "ssd"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "b"

    def test_node_affinity_required(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("a").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .zone("z1").obj())
        cs.create_node(make_node().name("b").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .zone("z2").obj())
        pod = (make_pod().name("p").req({"cpu": "1"})
               .node_affinity_in("topology.kubernetes.io/zone", ["z2"]).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "b"

    def test_preferred_node_affinity_scores(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("a").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .label("tier", "cold").obj())
        cs.create_node(make_node().name("b").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .label("tier", "hot").obj())
        pod = (make_pod().name("p").req({"cpu": "1"})
               .preferred_node_affinity(100, "tier", ["hot"]).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "b"

    def test_host_port_conflict(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        cs.create_node(make_node().name("n2").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        p1 = make_pod().name("p1").req({"cpu": "100m"}).host_port(8080).obj()
        cs.create_pod(p1)
        sched.schedule_one()
        p2 = make_pod().name("p2").req({"cpu": "100m"}).host_port(8080).obj()
        cs.create_pod(p2)
        sched.schedule_one()
        assert cs.bindings[p1.uid] != cs.bindings[p2.uid]

    def test_unschedulable_node_skipped(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("cordoned").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                       .unschedulable().obj())
        cs.create_node(make_node().name("ok").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "ok"

    def test_scheduling_gates_hold_pod(self):
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        pod = make_pod().name("gated").req({"cpu": "1"}).scheduling_gate("wait").obj()
        cs.create_pod(pod)
        assert not sched.schedule_one()  # nothing poppable
        assert len(sched.queue.unschedulable) == 1
        # remove the gate → pod becomes schedulable
        pod.scheduling_gates = []
        cs.update_pod(pod)
        sched.run_until_idle()
        assert cs.bindings[pod.uid] == "n1"


class TestTopologySpread:
    def test_do_not_schedule_respects_skew(self):
        cs, sched = new_scheduler()
        for i, z in [(0, "z1"), (1, "z1"), (2, "z2")]:
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 110})
                           .zone(z).obj())
        # 2 existing app pods in z1, 0 in z2 → next app pod must go z2
        for i, n in [(0, "n0"), (1, "n1")]:
            cs.create_pod(make_pod().name(f"pre{i}").label("app", "web").req({"cpu": "100m"}).node(n).obj())
        pod = (make_pod().name("p").label("app", "web").req({"cpu": "100m"})
               .spread_constraint(1, "topology.kubernetes.io/zone", match_labels={"app": "web"}).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "n2"

    def test_spread_sequence_balances_zones(self):
        cs, sched = new_scheduler()
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 2}").obj())
        pods = [
            (make_pod().name(f"p{i}").label("app", "web").req({"cpu": "100m"})
             .spread_constraint(1, "topology.kubernetes.io/zone", match_labels={"app": "web"}).obj())
            for i in range(10)
        ]
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        zone_count = {"z0": 0, "z1": 0}
        for p in pods:
            n = cs.bindings[p.uid]
            zone_count[f"z{int(n[1:]) % 2}"] += 1
        assert abs(zone_count["z0"] - zone_count["z1"]) <= 1


class TestInterPodAffinity:
    def test_required_anti_affinity_spreads(self):
        cs, sched = new_scheduler()
        for i in range(3):
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 110}).obj())
        pods = [
            (make_pod().name(f"p{i}").label("app", "db").req({"cpu": "100m"})
             .pod_affinity("kubernetes.io/hostname", {"app": "db"}, anti=True).obj())
            for i in range(3)
        ]
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        hosts = {cs.bindings[p.uid] for p in pods}
        assert len(hosts) == 3  # one per node

    def test_fourth_anti_affinity_pod_unschedulable(self):
        cs, sched = new_scheduler()
        for i in range(3):
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 110}).obj())
        pods = [
            (make_pod().name(f"p{i}").label("app", "db").req({"cpu": "100m"})
             .pod_affinity("kubernetes.io/hostname", {"app": "db"}, anti=True).obj())
            for i in range(4)
        ]
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        assert len(cs.bindings) == 3
        assert len(sched.queue.unschedulable) == 1

    def test_required_affinity_coschedules(self):
        cs, sched = new_scheduler()
        for i in range(3):
            cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 110}).obj())
        base = make_pod().name("base").label("app", "cache").req({"cpu": "100m"}).obj()
        cs.create_pod(base)
        sched.schedule_one()
        follower = (make_pod().name("f").req({"cpu": "100m"})
                    .pod_affinity("kubernetes.io/hostname", {"app": "cache"}).obj())
        cs.create_pod(follower)
        sched.schedule_one()
        assert cs.bindings[follower.uid] == cs.bindings[base.uid]

    def test_self_affinity_bootstrap(self):
        # A pod whose affinity matches its own labels can schedule on an
        # empty cluster (filtering.go satisfyPodAffinity special case).
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n0").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        pod = (make_pod().name("p").label("app", "x").req({"cpu": "100m"})
               .pod_affinity("kubernetes.io/hostname", {"app": "x"}).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert pod.uid in cs.bindings


    def test_self_affinity_bootstrap_requires_topology_key(self):
        # satisfyPodAffinity returns false when a node misses any term's
        # topology key — the bootstrap case never overrides that
        # (filtering.go:398-426).
        cs, sched = new_scheduler()
        n = make_node().name("nokey").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        n.labels.pop("kubernetes.io/hostname", None)
        cs.create_node(n)
        pod = (make_pod().name("p").label("app", "x").req({"cpu": "100m"})
               .pod_affinity("kubernetes.io/hostname", {"app": "x"}).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert pod.uid not in cs.bindings


    def test_bootstrap_checks_keys_across_all_terms(self):
        # Two affinity terms: first key present (0 matches), second key absent
        # — the missing-key check must survive the first term's miss.
        cs, sched = new_scheduler()
        cs.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        pod = (make_pod().name("p").label("app", "x").req({"cpu": "100m"})
               .pod_affinity("kubernetes.io/hostname", {"app": "x"})
               .pod_affinity("rack", {"app": "x"}).obj())
        cs.create_pod(pod)
        sched.schedule_one()
        assert pod.uid not in cs.bindings


class TestFitOnlyProfile:
    def test_fit_only(self):
        cs, sched = new_scheduler(profiles=fit_only_profiles)
        cs.create_node(make_node().name("n1").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        cs.create_pod(pod)
        sched.schedule_one()
        assert cs.bindings[pod.uid] == "n1"


class TestBackoff:
    def test_backoff_duration_doubles(self):
        from kubernetes_tpu.core.queue import PriorityQueue, QueuedPodInfo
        from kubernetes_tpu.core.node_info import PodInfo
        q = PriorityQueue()
        pod = make_pod().name("p").obj()
        qpi = QueuedPodInfo(pod_info=PodInfo.of(pod))
        qpi.attempts = 1
        assert q.backoff_duration(qpi) == 1.0
        qpi.attempts = 3
        assert q.backoff_duration(qpi) == 4.0
        qpi.attempts = 10
        assert q.backoff_duration(qpi) == 10.0  # capped


class TestZoneInterleavedOrder:
    """Snapshot node order follows NodeTree's zone round-robin
    (backend/cache/node_tree.go list(), wired via updateNodeInfoSnapshotList)."""

    def test_snapshot_order_interleaves_zones(self):
        cs = FakeClientset()
        sched = Scheduler(clientset=cs)
        # Two zones added in blocks: a-0 a-1 a-2 then b-0 b-1 b-2.
        for z, names in (("zone-a", ["a-0", "a-1", "a-2"]),
                         ("zone-b", ["b-0", "b-1", "b-2"])):
            for n in names:
                cs.create_node(make_node().name(n).capacity({"cpu": 4}).zone(z).obj())
        sched.cache.update_snapshot(sched.snapshot)
        order = [ni.name for ni in sched.snapshot.node_info_list]
        assert order == ["a-0", "b-0", "a-1", "b-1", "a-2", "b-2"]

    def test_zone_change_rebuckets(self):
        cs = FakeClientset()
        sched = Scheduler(clientset=cs)
        cs.create_node(make_node().name("a-0").capacity({"cpu": 4}).zone("zone-a").obj())
        cs.create_node(make_node().name("b-0").capacity({"cpu": 4}).zone("zone-b").obj())
        sched.cache.update_snapshot(sched.snapshot)
        cs.update_node(make_node().name("a-0").capacity({"cpu": 4}).zone("zone-b").obj())
        sched.cache.update_snapshot(sched.snapshot)
        order = [ni.name for ni in sched.snapshot.node_info_list]
        assert order == ["b-0", "a-0"]
        assert sched.cache.node_tree.num_nodes == 2


def test_update_pod_invalidates_signature_memo():
    """Mutate-and-republish of the SAME pod object must re-sign: the memo
    drops at the API boundary (clientset.update_pod), so a changed spec
    (tolerations are signed) produces a different signature."""
    from kubernetes_tpu.api.types import Toleration
    from kubernetes_tpu.core import FakeClientset, Scheduler
    from kubernetes_tpu.testing.wrappers import make_pod

    cs = FakeClientset()
    s = Scheduler(clientset=cs)
    fw = s.profiles["default-scheduler"]
    proto = make_pod().name("proto").req({"cpu": "1"}).obj()
    pod = proto.clone_from_template("p0")
    pod.scheduling_gates = ["hold"]  # keep it parked, not scheduled
    cs.create_pod(pod)
    sig_before = fw.sign_pod(pod)
    # In-place spec change republished through the API.
    pod.tolerations = [Toleration(key="dedicated", operator="Exists")]
    pod.scheduling_gates = []
    cs.update_pod(pod)
    sig_after = fw.sign_pod(pod)
    assert sig_before != sig_after, "stale signature served after update_pod"
    # The template prototype's memo must be unaffected by the divergent clone.
    assert fw.sign_pod(proto.clone_from_template("p1")) == sig_before


def test_gang_simulation_sees_assumed_anti_affinity():
    """Mid-simulation assumed members must be visible to later members'
    InterPodAffinity PreFilter (snapshot sublists stay consistent): a gang
    whose second member would violate the first member's required
    anti-affinity must NOT commit (regression: the sublist shortcut read a
    stale have_pods_with_required_anti_affinity_list)."""
    from kubernetes_tpu.api.types import PodGroup

    cs = FakeClientset()
    sched = Scheduler(clientset=cs, deterministic_ties=True)
    for i in range(2):
        cs.create_node(
            make_node().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 110}).obj())
    cs.create_pod_group(PodGroup(name="g", min_count=2))
    a = (make_pod().name("a").labels({"app": "x"})
         .pod_affinity("kubernetes.io/hostname", {"app": "x"}, anti=True)
         .req({"cpu": "100m"}).obj())
    a.pod_group = "g"
    b = (make_pod().name("b").labels({"app": "x"})
         .req({"cpu": "100m"}).obj())
    b.pod_group = "g"
    cs.create_pod(a)
    cs.create_pod(b)
    sched.run_until_idle()
    bound = {cs.bindings.get(a.uid), cs.bindings.get(b.uid)}
    # Both scheduled (2 nodes available) but never co-located.
    assert None not in bound and len(bound) == 2, bound


def test_queueing_hint_fns_filter_requeues():
    """QueueingHintFn callbacks (scheduling_queue.go:582 isPodWorthRequeuing):
    a Node/Add that cannot help a NodeResourcesFit rejection does NOT requeue
    the pod; one that can, does. Same for NodeAffinity and TaintToleration."""
    cs = FakeClientset()
    sched = Scheduler(clientset=cs)
    cs.create_node(make_node().name("small").capacity({"cpu": "1", "pods": 10}).obj())

    big = make_pod().name("big").req({"cpu": "8"}).obj()
    cs.create_pod(big)
    sched.run_until_idle()
    assert cs.bindings.get(big.uid) is None
    assert "big" not in [q.pod.name for q in sched.queue.active_q.items()]

    # A too-small node: the Fit hint must SKIP (no requeue).
    cs.create_node(make_node().name("small2").capacity({"cpu": "2", "pods": 10}).obj())
    assert sched.queue.active_q.get(big.uid) is None
    assert sched.queue.backoff_q.get(big.uid) is None
    assert big.uid in sched.queue.unschedulable

    # A big-enough node: the hint queues it, and it schedules.
    cs.create_node(make_node().name("big-node").capacity({"cpu": "16", "pods": 10}).obj())
    assert big.uid not in sched.queue.unschedulable
    sched.queue.flush_backoff_completed()
    sched.run_until_idle()
    import time as _t
    deadline = _t.monotonic() + 12
    while cs.bindings.get(big.uid) is None and _t.monotonic() < deadline:
        _t.sleep(0.1)
        sched.queue.flush_backoff_completed()
        sched.run_until_idle()
    assert cs.bindings.get(big.uid) == "big-node"


def test_queueing_hint_node_affinity_and_taints():
    cs = FakeClientset()
    sched = Scheduler(clientset=cs)
    cs.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
    pod = (make_pod().name("picky").req({"cpu": "1"})
           .node_selector({"tier": "gold"}).obj())
    cs.create_pod(pod)
    sched.run_until_idle()
    assert pod.uid in sched.queue.unschedulable
    assert sched.queue.unschedulable[pod.uid].unschedulable_plugins == {"NodeAffinity"}

    # Node without the selector label: NodeAffinity hint skips.
    cs.create_node(make_node().name("plain").capacity({"cpu": "8", "pods": 10}).obj())
    assert pod.uid in sched.queue.unschedulable

    # A tainted node WITH the label: NodeAffinity hint queues (taints are
    # TaintToleration's concern, and it rejected nothing yet).
    cs.create_node(make_node().name("gold").capacity({"cpu": "8", "pods": 10})
                   .label("tier", "gold").obj())
    assert pod.uid not in sched.queue.unschedulable
