"""Placement-based gang scheduling (schedule_one_podgroup.go:971
podGroupSchedulingPlacementAlgorithm + topology_placement.go +
podgroup_pods_count.go + findBestPodGroupPlacement :1173).

A topology-constrained PodGroup generates one candidate placement per
topology domain, simulates the group against each, gates with
PlacementFeasible (GangScheduling min_count), scores candidates with
PlacementScore plugins, and commits the best — packing the gang into ONE
domain instead of spreading it like member-wise scheduling would.
"""

import pytest

from kubernetes_tpu.api.types import PodGroup
from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.registry import gang_placement_profiles
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _sched(**kw):
    cs = FakeClientset()
    s = Scheduler(clientset=cs, profile_factory=gang_placement_profiles,
                  deterministic_ties=True, **kw)
    return cs, s


def _gang(cs, name, size, cpu="1", min_count=None, topology_keys=(ZONE,)):
    cs.create_pod_group(PodGroup(
        name=name, min_count=min_count if min_count is not None else size,
        topology_keys=tuple(topology_keys)))
    pods = []
    for i in range(size):
        p = make_pod().name(f"{name}-{i}").req({"cpu": cpu}).obj()
        p.pod_group = name
        cs.create_pod(p)
        pods.append(p)
    return pods


def _zones_of(cs, pods):
    return {cs.nodes[p.node_name].labels[ZONE] for p in pods if p.node_name}


class TestPlacementAlgorithm:
    def test_gang_packs_into_one_zone(self):
        cs, s = _sched()
        # 3 zones x 4 nodes; without placements a 4-pod gang would spread
        # (LeastAllocated balances), with the topology constraint it must
        # land entirely inside one zone.
        for i in range(12):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 3}").obj())
        pods = _gang(cs, "train", 4)
        s.run_until_idle()
        assert all(p.node_name for p in pods), [p.node_name for p in pods]
        assert len(_zones_of(cs, pods)) == 1

    def test_best_placement_most_members(self):
        cs, s = _sched()
        # z0 fits only 2 gang pods, z1 fits all 4: PodGroupPodsCount must
        # pick z1 even though z0 sorts first.
        for i in range(2):
            cs.create_node(make_node().name(f"small{i}")
                           .capacity({"cpu": 4, "memory": "32Gi", "pods": 110})
                           .zone("z0").obj())
        for i in range(4):
            cs.create_node(make_node().name(f"big{i}")
                           .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                           .zone("z1").obj())
        pods = _gang(cs, "train", 4, cpu="4", min_count=2)
        s.run_until_idle()
        placed = [p for p in pods if p.node_name]
        assert len(placed) == 4
        assert _zones_of(cs, placed) == {"z1"}

    def test_min_count_gate_rejects_thin_domains(self):
        cs, s = _sched()
        # Every zone fits only 2 of the 3 required members: no placement is
        # feasible, the group parks unschedulable, nothing commits.
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 2, "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 2}").obj())
        pods = _gang(cs, "train", 3, cpu="2", min_count=3)
        s.run_until_idle()
        assert all(not p.node_name for p in pods)
        assert s.scheduled == 0

    def test_partial_gang_when_min_count_met(self):
        cs, s = _sched()
        # One zone fits 3 of 4 members with min_count 2: the placement is
        # feasible, 3 commit, the 4th member fails individually.
        for i in range(3):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 2, "memory": "32Gi", "pods": 110})
                           .zone("z0").obj())
        pods = _gang(cs, "train", 4, cpu="2", min_count=2)
        s.run_until_idle()
        placed = [p for p in pods if p.node_name]
        assert len(placed) == 3
        assert _zones_of(cs, placed) == {"z0"}

    def test_scheduled_members_pin_the_domain(self):
        cs, s = _sched()
        for i in range(6):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 3}").obj())
        # A group pod already bound in z2 forces the generator to emit only
        # the z2 placement (topology_placement.go requiredDomain).
        cs.create_pod_group(PodGroup(name="train", min_count=2,
                                     topology_keys=(ZONE,)))
        bound = make_pod().name("train-bound").req({"cpu": "1"}).obj()
        bound.pod_group = "train"
        bound.node_name = "n2"  # z2
        cs.create_pod(bound)
        pods = []
        for i in range(2):
            p = make_pod().name(f"train-{i}").req({"cpu": "1"}).obj()
            p.pod_group = "train"
            cs.create_pod(p)
            pods.append(p)
        s.run_until_idle()
        assert all(p.node_name for p in pods)
        assert _zones_of(cs, pods) == {"z2"}

    def test_no_topology_keys_uses_default_algorithm(self):
        cs, s = _sched()
        for i in range(4):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 2}").obj())
        pods = _gang(cs, "plain", 4, topology_keys=())
        s.run_until_idle()
        assert all(p.node_name for p in pods)
        # default member-wise algorithm spreads across zones (LeastAllocated)
        assert len(_zones_of(cs, pods)) == 2


class TestPlacementCommitState:
    def test_commit_reuses_winning_simulation_cycle_state(self):
        """The committed members must receive the CycleState from the WINNING
        placement simulation — stateful Reserve/PreBind plugins (e.g.
        VolumeBinding) read PreFilter data written during the simulation
        (schedule_one_podgroup.go algorithmResult.GetCycleState →
        submitPodGroupAlgorithmResult)."""
        from kubernetes_tpu.core.framework import OK, CycleState
        from kubernetes_tpu.core.registry import build_framework
        from kubernetes_tpu.core.registry import GANG_PLACEMENT_PLUGINS

        seen = {}

        class StateProbe:
            name = "StateProbe"

            def pre_filter(self, state, pod, nodes):
                state.write("probe/" + pod.name, "sim")
                return None, OK

            def reserve(self, state, pod, node_name):
                seen[pod.name] = state.read("probe/" + pod.name)
                return OK

        def profiles(handle):
            fw = build_framework(handle, plugins=GANG_PLACEMENT_PLUGINS)
            probe = StateProbe()
            fw.pre_filter_plugins.append(probe)
            fw.reserve_plugins.append(probe)
            return {"default-scheduler": fw}

        cs = FakeClientset()
        s = Scheduler(clientset=cs, profile_factory=profiles,
                      deterministic_ties=True)
        for i in range(6):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": 8, "memory": "32Gi", "pods": 110})
                           .zone(f"z{i % 2}").obj())
        pods = _gang(cs, "probe", 3)
        s.run_until_idle()
        assert all(p.node_name for p in pods)
        # Every committed member's Reserve saw the simulation-written state.
        assert seen == {p.name: "sim" for p in pods}, seen


def test_pod_group_state_store_tracks_bound_members():
    """The persistent scheduled-group-pods index (core/podgroupstate.py,
    podgroupstate.go analogue) follows binds and deletes incrementally and
    pins a partially-scheduled gang's domain without cluster scans."""
    from kubernetes_tpu.api.types import PodGroup

    cs, s = _sched()
    for i in range(6):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "pods": 110})
                       .zone(f"z{i % 2}").obj())
    cs.create_pod_group(PodGroup(name="g", min_count=2, topology_keys=(ZONE,)))
    pods = []
    for i in range(2):
        p = make_pod().name(f"m{i}").req({"cpu": "1"}).obj()
        p.pod_group = "g"
        cs.create_pod(p)
        pods.append(p)
    s.run_until_idle()
    store = s.pod_group_state
    assert store.count("default", "g") == 2
    gen = store.generation
    cs.delete_pod(pods[0])
    assert store.count("default", "g") == 1
    assert store.generation > gen


class TestDevicePlacementSpread:
    """Placement gangs whose MEMBERS carry topology-spread constraints ride
    the stacked device evaluation (round-4 VERDICT item 4): the restricted
    spread tables are rebuilt per placement (spread_overrides), matching the
    host oracle's assume_placement-restricted PreFilter state."""

    HOSTNAME = "kubernetes.io/hostname"

    def _cluster(self, cs, zones=3, per_zone=4, cpu=8):
        for i in range(zones * per_zone):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": cpu, "memory": "32Gi",
                                      "pods": 110})
                           .zone(f"z{i % zones}").obj())

    def _spread_gang(self, cs, name, size, max_skew=1, key=None):
        cs.create_pod_group(PodGroup(
            name=name, min_count=size, topology_keys=(ZONE,)))
        pods = []
        for i in range(size):
            p = (make_pod().name(f"{name}-{i}").req({"cpu": "1"})
                 .labels({"gang": name})
                 .spread_constraint(max_skew, key or self.HOSTNAME,
                                    "DoNotSchedule", {"gang": name})
                 .obj())
            p.pod_group = name
            cs.create_pod(p)
            pods.append(p)
        return pods

    def _pair(self, fn):
        from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
        out = []
        for cls in (Scheduler, TPUScheduler):
            cs = FakeClientset()
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            s = cls(clientset=cs, profile_factory=gang_placement_profiles,
                    **kw)
            fn(cs, s)
            s.run_until_idle()
            out.append((cs, s))
        return out

    def test_hostname_spread_members_match_host(self):
        """maxSkew=1 over hostname forces one member per node INSIDE the
        chosen zone — the placement-restricted domain set."""
        def fn(cs, s):
            self._cluster(cs)
            self._spread_gang(cs, "train", 4)

        (cs_h, host), (cs_d, dev) = self._pair(fn)
        h = {p.name: p.node_name for p in cs_h.pods.values()}
        d = {p.name: p.node_name for p in cs_d.pods.values()}
        assert h == d, {k: (h[k], d.get(k)) for k in h if h[k] != d.get(k)}
        assert all(h.values())
        # spread satisfied: 4 distinct nodes, one zone
        assert len(set(h.values())) == 4
        assert len(_zones_of(cs_h, list(cs_h.pods.values()))) == 1
        assert dev.placement_device_evals > 0, "device placement path off"

    def test_skew_infeasible_domain_rejected(self):
        """A zone with too few nodes for the skew constraint must lose to a
        bigger zone — the restricted domain count decides feasibility."""
        def fn(cs, s):
            # z0: 2 nodes, z1: 4 nodes; gang of 4 with hostname skew 1 only
            # fits in z1.
            for i in range(2):
                cs.create_node(make_node().name(f"s{i}")
                               .capacity({"cpu": 8, "pods": 110})
                               .zone("z0").obj())
            for i in range(4):
                cs.create_node(make_node().name(f"b{i}")
                               .capacity({"cpu": 8, "pods": 110})
                               .zone("z1").obj())
            self._spread_gang(cs, "train", 4)

        (cs_h, host), (cs_d, dev) = self._pair(fn)
        h = {p.name: p.node_name for p in cs_h.pods.values()}
        d = {p.name: p.node_name for p in cs_d.pods.values()}
        assert h == d
        assert all(v.startswith("b") for v in h.values()), h
        assert dev.placement_device_evals > 0

    def test_fuzz_spread_gangs(self):
        import random
        for seed in range(4):
            def fn(cs, s, seed=seed):
                rng = random.Random(seed)
                zones = rng.choice([2, 3, 4])
                per = rng.choice([3, 4, 5])
                self._cluster(cs, zones=zones, per_zone=per,
                              cpu=rng.choice([4, 8]))
                for g in range(3):
                    self._spread_gang(
                        cs, f"g{g}", rng.choice([2, 3]),
                        max_skew=rng.choice([1, 2]),
                        key=rng.choice([self.HOSTNAME, ZONE]))

            (cs_h, host), (cs_d, dev) = self._pair(fn)
            h = {p.name: p.node_name for p in cs_h.pods.values()}
            d = {p.name: p.node_name for p in cs_d.pods.values()}
            assert h == d, (seed, {k: (h[k], d.get(k))
                                   for k in h if h[k] != d.get(k)})
