"""Warm/live dispatch trace identity.

warm_for exists to put XLA compilation OUTSIDE measured windows; that only
works if every live dispatch is call-signature-identical to the warm ones
(static kwargs are part of jit's cache-key pytree structure — an omitted-vs-
explicit kwarg is a different structure and retraces). Round 2's headline
"regression" (TopologySpreading at 0.22x baseline) was exactly such a
mismatch: a ~1min compile inside every measured window. These tests pin the
invariant with jit's trace-cache size so it can never silently return.
"""

import numpy as np
import pytest

from kubernetes_tpu.core import FakeClientset
from kubernetes_tpu.models import TPUScheduler
from kubernetes_tpu.ops.kernel import schedule_batch
from kubernetes_tpu.testing import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _cache_size():
    try:
        return schedule_batch._cache_size()
    except AttributeError:  # pragma: no cover - jax internals moved
        pytest.skip("jit cache size introspection unavailable")


def _cluster(n_nodes=40):
    cs = FakeClientset()
    s = TPUScheduler(clientset=cs)
    for i in range(n_nodes):
        cs.create_node(
            make_node().name(f"n{i}")
            .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
            .zone(f"z{i % 4}").obj())
    return cs, s


@pytest.mark.parametrize("template", ["basic", "spread", "anti"])
def test_no_retrace_after_warm(template):
    cs, s = _cluster()

    def pod(name):
        b = make_pod().name(name).req({"cpu": "100m"})
        if template == "spread":
            b = b.label("app", "t").spread_constraint(
                1, ZONE, "DoNotSchedule", {"app": "t"})
        elif template == "anti":
            b = b.label("app", "t").pod_affinity(
                "kubernetes.io/hostname", {"app": "t"}, anti=True)
        return b.obj()

    s.warm_for(pod("warm-template"))
    warmed = _cache_size()
    # Enough pods for two chained batches: exercises the fresh-carry AND
    # chained-carry live dispatches.
    for i in range(30):
        cs.create_pod(pod(f"p{i}"))
    s.run_until_idle()
    assert s.scheduled == 30 and s.host_path_pods == 0
    assert _cache_size() == warmed, (
        "live dispatch retraced schedule_batch after warm_for — a compile "
        "would land inside the measured window on real hardware")
