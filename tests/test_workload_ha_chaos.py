"""Workload controller-manager HA chaos (ISSUE PR-17, docs/RESILIENCE.md §
workload controllers): two manager PROCESSES race the shared
`workload-controller-manager` lease over a REPLICATED control plane, and we
``kill -9`` the ACTIVE one (a) mid-rolling-update and (b) mid-eviction-wave.
The standby must take over inside the lease TTL and converge exactly-once:
deterministic pod names + create-409-is-success mean the takeover's first
ACTIVE pass finishes whatever the dead incumbent half-did without
double-creating or stranding a replica, and the server-side PDB
precondition keeps the workload's BOUND count at or above minAvailable at
every single poll of the wave."""

import json
import threading
import time
from urllib import request as urlrequest
from urllib.error import HTTPError, URLError

import pytest

from kubernetes_tpu.controllers.evictor import intent_for
from kubernetes_tpu.controllers.workload import replica_name
from kubernetes_tpu.core.apiserver import node_to_wire
from kubernetes_tpu.shard.harness import (_env, _repo_root,
                                          start_workload_manager,
                                          stop_controller)
from kubernetes_tpu.testing.faults import ReplicaSet, drain_pipe
from kubernetes_tpu.testing.wrappers import make_node

APP = "app"


def _call(base, method, path, body=None, timeout=30.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urlrequest.Request(base + path, data=data, method=method,
                            headers={"Content-Type": "application/json"})
    with urlrequest.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def _any(urls, method, path, body=None, timeout=10.0):
    """Leader-seeking raw call: try every replica, follow whoever answers
    (followers bounce writes with 421; a freshly-killed process refuses).
    Raises the last error if nobody serves the verb."""
    last = None
    for url in urls:
        try:
            return _call(url, method, path, body, timeout=timeout)
        except HTTPError as e:
            if e.code in (421, 503):
                last = e
                continue
            raise
        except URLError as e:
            last = e
            continue
    raise last if last is not None else AssertionError("no replicas")


def _get_text(base, path, timeout=10.0):
    with urlrequest.urlopen(base + path, timeout=timeout) as resp:
        return resp.read().decode()


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"series {name} not exposed")


def _wait(pred, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _pods(urls, app):
    got = _any(urls, "GET", "/api/v1/pods") or []
    return [p for p in got if (p.get("labels") or {}).get(APP) == app
            and not p.get("deletionTs")]


def _active_manager(managers):
    """(proc, metrics_url) of the manager whose gauge reads ACTIVE, or
    None while the lease race is still unsettled."""
    for proc, url in managers:
        if proc.poll() is not None:
            continue
        try:
            text = _get_text(url, "/metrics", timeout=5.0)
        except Exception:  # noqa: BLE001 - scrape raced a death
            continue
        if _metric(text, "workload_manager_active") == 1:
            return proc, url
    return None


class _Binder:
    """Paced binder thread: binds pending pods of one app label onto a
    rotating target list, one pod per beat. Swapping `targets` re-aims
    rescheduling (the doomed→healthy flip in the eviction-wave test);
    setting it empty pauses binding entirely."""

    def __init__(self, urls, app, targets, beat=0.2):
        self.urls = urls
        self.app = app
        self.targets = list(targets)
        self.beat = beat
        self.binds = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        i = 0
        while not self._stop.is_set():
            targets = list(self.targets)
            try:
                pending = [p for p in _pods(self.urls, self.app)
                           if not p.get("nodeName")]
            except Exception:  # noqa: BLE001 - leader churn mid-poll
                pending = []
            if pending and targets:
                p = sorted(pending, key=lambda q: q["name"])[0]
                node = targets[i % len(targets)]
                i += 1
                try:
                    _any(self.urls, "POST",
                         f"/api/v1/pods/{p['uid']}/binding",
                         {"node": node})
                    self.binds += 1
                except HTTPError as e:
                    if e.code not in (404, 409):  # gone / already bound
                        raise
            self._stop.wait(self.beat)

    def stop(self):
        self._stop.set()
        self._t.join(timeout=5)


class _FloorWatch:
    """Polls the live pod census and records every observation where the
    BOUND count of the guarded app dips below the PDB's minAvailable —
    the 'never observed violated at any poll' assertion is `violations ==
    []` at the end."""

    def __init__(self, urls, app, min_available, legal_names):
        self.urls = urls
        self.app = app
        self.min_available = min_available
        self.legal_names = set(legal_names)
        self.violations = []
        self.aliens = []
        self.polls = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                live = _pods(self.urls, self.app)
            except Exception:  # noqa: BLE001 - leader churn mid-poll
                self._stop.wait(0.05)
                continue
            self.polls += 1
            bound = sum(1 for p in live if p.get("nodeName"))
            if bound < self.min_available:
                self.violations.append(bound)
            for p in live:
                if p["name"] not in self.legal_names:
                    self.aliens.append(p["name"])
            self._stop.wait(0.05)

    def stop(self):
        self._stop.set()
        self._t.join(timeout=5)


def _mk_nodes(urls, names, cpu=16, pods=110):
    for n in names:
        node = (make_node().name(n)
                .capacity({"cpu": cpu, "memory": "64Gi", "pods": pods})
                .obj())
        _any(urls, "POST", "/api/v1/nodes", node_to_wire(node))


def _spawn_pair(rs, lease_ttl):
    repo, env = _repo_root(), _env()
    managers, tails = [], []
    for i in range(2):
        proc, murl = start_workload_manager(
            rs.follower_urls[0], repo, env, identity=f"wm-{i}",
            fallbacks=[rs.follower_urls[1], rs.leader_url],
            lease_ttl=lease_ttl, tick=0.1)
        managers.append((proc, murl))
        tails.append(drain_pipe(proc))
    return managers, tails


@pytest.mark.chaos
def test_active_kill9_mid_rolling_update_exactly_once(tmp_path):
    """SIGKILL the ACTIVE manager in the middle of a rolling update. The
    standby CASes the lease inside the TTL and finishes the rollout:
    every rev-1 name the dead incumbent already minted answers 409
    (success), every missing one is created exactly once, the old
    ReplicaSet drains through the PDB-guarded voluntary path, and the
    final census is EXACTLY the rev-1 want-set — no duplicates, no
    strays, and the `api` workload's bound count never observed below
    minAvailable=2 at any poll."""
    LEASE = 1.2
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=1.5, snapshot_every=100_000)
    urls = [rs.leader_url] + list(rs.follower_urls)
    managers, tails = [], []
    binder = watch = None
    try:
        _mk_nodes(urls, ["n0", "n1"])
        _any(urls, "POST", "/api/v1/pdbs",
             {"name": "api-pdb", "namespace": "default",
              "minAvailable": 2, "matchLabels": {APP: "api"}})
        dep = {"name": "api", "namespace": "default", "replicas": 4,
               "revision": 0, "maxSurge": 1, "maxUnavailable": 1,
               "template": {"labels": {APP: "api"}, "cpuMilli": 100}}
        _any(urls, "POST", "/api/v1/deployments", dep)
        managers, tails = _spawn_pair(rs, LEASE)

        want0 = {replica_name("api-0", 0, i) for i in range(4)}
        want1 = {replica_name("api-1", 1, i) for i in range(4)}
        binder = _Binder(urls, "api", ["n0", "n1"], beat=0.25)

        def _rev0_settled():
            live = _pods(urls, "api")
            return ({p["name"] for p in live} == want0
                    and all(p.get("nodeName") for p in live))
        _wait(_rev0_settled, timeout=60, msg="revision-0 rollout")
        _wait(lambda: _active_manager(managers) is not None,
              timeout=30, msg="an ACTIVE manager")
        active_proc, _ = _active_manager(managers)

        # From here to quiesce the PDB floor must hold at EVERY poll, and
        # no pod outside want0|want1 may ever exist.
        watch = _FloorWatch(urls, "api", 2, want0 | want1)
        _any(urls, "PUT", "/api/v1/deployments/default/api",
             dict(dep, revision=1))

        def _mid_rollout():
            names = {p["name"] for p in _pods(urls, "api")}
            return bool(names & want1) and bool(names & want0)
        _wait(_mid_rollout, timeout=30, msg="rollout under way")
        active_proc.kill()  # SIGKILL: no lease release, no goodbye
        t_kill = time.monotonic()
        survivor = next((p, u) for p, u in managers if p is not active_proc)

        _wait(lambda: _active_manager(managers) == survivor,
              timeout=LEASE * 8, msg="standby takeover")
        assert time.monotonic() - t_kill <= LEASE * 6  # inside TTL window

        def _rev1_settled():
            live = _pods(urls, "api")
            return ({p["name"] for p in live} == want1
                    and all(p.get("nodeName") for p in live))
        _wait(_rev1_settled, timeout=90, msg="takeover finishes rollout")
        # old ReplicaSet garbage-collected, only api-1 remains
        _wait(lambda: {w["name"] for w in
                       (_any(urls, "GET", "/api/v1/replicasets") or [])
                       if w.get("deployment") == "api"} == {"api-1"},
              timeout=30, msg="old RS GC")
        watch.stop()
        assert watch.polls > 0
        assert watch.violations == [], watch.violations
        assert watch.aliens == [], watch.aliens
        # zero duplicate live pods at quiesce (names are the uids)
        final = [p["name"] for p in _pods(urls, "api")]
        assert sorted(final) == sorted(set(final)) and len(final) == 4

        stats = stop_controller(survivor[0],
                                tails[managers.index(survivor)])
        assert stats is not None
        # the survivor really was a STANDBY that took over, and the seam
        # swallowed whatever the incumbent had already minted
        assert stats["takeovers"] == 1 and stats["standby_ticks"] > 0
        rs_stats = stats["replicasets"]
        assert rs_stats["pods_created"] + rs_stats["creates_409"] >= 1
    finally:
        if binder is not None:
            binder.stop()
        if watch is not None:
            watch.stop()
        for proc, _ in managers:
            if proc.poll() is None:
                proc.kill()
        rs.stop()


@pytest.mark.chaos
def test_active_kill9_mid_eviction_wave_pdb_floor_holds(tmp_path):
    """A PDB-guarded eviction wave drains a doomed node pair while the
    ACTIVE manager is SIGKILLed mid-wave. The first eviction burst lands
    with rebinding paused, so the server's precondition arithmetic is
    exact: with 8 bound and minAvailable=5, exactly 3 evictions commit
    and the rest answer 429 DisruptionBudget. Then rebinding aims at the
    healthy pair, the blocked evictions retry, a chaos delete kills one
    evicted replica outright — and the surviving manager re-mints it
    under the SAME deterministic name while the wave finishes. Quiesce:
    all 8 replicas bound on healthy nodes, zero duplicates, bound count
    never observed below the floor."""
    LEASE = 1.2
    rs = ReplicaSet(str(tmp_path / "replicas"), followers=2,
                    repl_lease=1.5, snapshot_every=100_000)
    urls = [rs.leader_url] + list(rs.follower_urls)
    managers, tails = [], []
    binder = watch = None
    doomed, healthy = ["d0", "d1"], ["h0", "h1"]
    try:
        _mk_nodes(urls, doomed)  # healthy pair arrives later
        _any(urls, "POST", "/api/v1/pdbs",
             {"name": "web-pdb", "namespace": "default",
              "minAvailable": 5, "matchLabels": {APP: "web"}})
        _any(urls, "POST", "/api/v1/deployments",
             {"name": "web", "namespace": "default", "replicas": 8,
              "revision": 0, "maxSurge": 1, "maxUnavailable": 1,
              "template": {"labels": {APP: "web"}, "cpuMilli": 100}})
        managers, tails = _spawn_pair(rs, LEASE)

        want = {replica_name("web-0", 0, i) for i in range(8)}
        binder = _Binder(urls, "web", doomed, beat=0.1)
        _wait(lambda: ({p["name"] for p in _pods(urls, "web")} == want
                       and all(p.get("nodeName") in doomed
                               for p in _pods(urls, "web"))),
              timeout=60, msg="initial placement on doomed pair")
        _mk_nodes(urls, healthy)
        binder.targets = []  # pause rebinding: burst arithmetic is exact
        time.sleep(0.3)  # let an in-flight bind beat drain

        watch = _FloorWatch(urls, "web", 5, want)
        before = _get_text(rs.leader_url, "/metrics")
        victims = [(p["uid"], p["nodeName"])
                   for p in sorted(_pods(urls, "web"),
                                   key=lambda p: p["name"])]
        assert len(victims) == 8
        committed, blocked = [], []
        for uid, node in victims:
            try:
                _any(urls, "POST", f"/api/v1/pods/{uid}/eviction",
                     {"intent": intent_for(uid, node), "node": node})
                committed.append((uid, node))
            except HTTPError as e:
                assert e.code == 429, e.code
                assert "DisruptionBudget" in e.read().decode()
                blocked.append((uid, node))
        # exact precondition arithmetic: 8 bound, floor 5 → 3 commits
        assert len(committed) == 3 and len(blocked) == 5
        after = _get_text(rs.leader_url, "/metrics")
        assert (_metric(after, "apiserver_pod_evictions_total")
                - _metric(before, "apiserver_pod_evictions_total")) == 3
        assert (_metric(after,
                        "apiserver_pod_evictions_budget_denied_total")
                - _metric(before,
                          "apiserver_pod_evictions_budget_denied_total")
                ) == 5

        _wait(lambda: _active_manager(managers) is not None,
              timeout=30, msg="an ACTIVE manager")
        active_proc, _ = _active_manager(managers)
        # chaos: one already-evicted (pending) replica dies outright —
        # an involuntary delete, invisible to the PDB's BOUND arithmetic
        dead_uid = committed[0][0]
        _any(urls, "DELETE", f"/api/v1/pods/{dead_uid}")
        active_proc.kill()  # SIGKILL the ACTIVE mid-wave
        t_kill = time.monotonic()
        survivor = next((p, u) for p, u in managers if p is not active_proc)

        binder.targets = healthy  # rebinding resumes, aimed off the wreck
        retry_stop = threading.Event()

        def _retry_wave():
            queue = list(blocked)
            while queue and not retry_stop.is_set():
                uid, node = queue.pop(0)
                try:
                    _any(urls, "POST", f"/api/v1/pods/{uid}/eviction",
                         {"intent": intent_for(uid, node), "node": node})
                except HTTPError as e:
                    if e.code == 429:
                        queue.append((uid, node))  # still at the floor
                    elif e.code not in (404, 409):
                        raise
                retry_stop.wait(0.2)
        retrier = threading.Thread(target=_retry_wave, daemon=True)
        retrier.start()

        _wait(lambda: _active_manager(managers) == survivor,
              timeout=LEASE * 8, msg="standby takeover")
        assert time.monotonic() - t_kill <= LEASE * 6

        def _settled():
            live = _pods(urls, "web")
            return ({p["name"] for p in live} == want
                    and all(p.get("nodeName") in healthy for p in live))
        _wait(_settled, timeout=90, msg="wave drained, fleet rebound")
        retry_stop.set()
        retrier.join(timeout=10)
        watch.stop()
        assert watch.polls > 0
        assert watch.violations == [], watch.violations
        assert watch.aliens == [], watch.aliens
        final = [p["name"] for p in _pods(urls, "web")]
        assert sorted(final) == sorted(set(final)) and len(final) == 8
        # every victim evicted exactly once: 3 burst + 5 retried commits
        end = _get_text(rs.leader_url, "/metrics")
        assert (_metric(end, "apiserver_pod_evictions_total")
                - _metric(before, "apiserver_pod_evictions_total")) == 8

        stats = stop_controller(survivor[0],
                                tails[managers.index(survivor)])
        assert stats is not None
        assert stats["takeovers"] == 1 and stats["standby_ticks"] > 0
        # the chaos-killed replica came back through the takeover's seam
        assert stats["replicasets"]["pods_created"] >= 1
    finally:
        if binder is not None:
            binder.stop()
        if watch is not None:
            watch.stop()
        for proc, _ in managers:
            if proc.poll() is None:
                proc.kill()
        rs.stop()
