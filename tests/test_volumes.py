"""Volume plugins: VolumeBinding, NodeVolumeLimits, VolumeZone,
VolumeRestrictions (reference plugins/volumebinding, nodevolumelimits/csi.go,
volumezone, volumerestrictions)."""

from kubernetes_tpu.api.labels import IN, Requirement
from kubernetes_tpu.api.storage import (
    RWO,
    RWOP,
    WAIT_FOR_FIRST_CONSUMER,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.api.types import NodeSelector, NodeSelectorTerm, Volume
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _pv_on(name, node_name, capacity="10Gi", sc="fast", **kw):
    return PersistentVolume.of(
        name, capacity, storage_class=sc,
        node_affinity=NodeSelector(terms=(NodeSelectorTerm(
            match_fields=(Requirement("metadata.name", IN, (node_name,)),)),)),
        **kw)


def _pod_with_pvc(name, pvc_name, cpu="100m"):
    p = make_pod().name(name).req({"cpu": cpu}).obj()
    p.volumes.append(Volume(name="data", pvc_name=pvc_name))
    return p


class TestVolumeBinding:
    def test_bound_pvc_node_affinity(self):
        s = Scheduler(deterministic_ties=True)
        for i in range(3):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        pv = _pv_on("pv-1", "n2")
        pvc = PersistentVolumeClaim.of("claim", "5Gi", storage_class="fast",
                                       volume_name="pv-1")
        s.clientset.create_pv(pv)
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "claim"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n2"]

    def test_unbound_immediate_is_unresolvable(self):
        s = Scheduler()
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(name="std", provisioner="x"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c", "1Gi", storage_class="std"))
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert s.scheduled == 0 and s.failures >= 1

    def test_wait_for_first_consumer_binds_pv(self):
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        s.clientset.create_pv(_pv_on("pv-a", "n1", sc="wffc"))
        pvc = PersistentVolumeClaim.of("c", "5Gi", storage_class="wffc")
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]
        assert pvc.volume_name == "pv-a"
        assert s.clientset.pvs["pv-a"].claim_ref == "default/c"

    def test_wffc_dynamic_provisioning(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", provisioner="csi.example.com",
            volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        pvc = PersistentVolumeClaim.of("c", "5Gi", storage_class="wffc")
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert s.scheduled == 1
        assert pvc.volume_name.startswith("pvc-")  # provisioned PV

    def test_two_claims_one_pv_conflict(self):
        """Second pod must not reuse the PV the first pod's claim assumed."""
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        s.clientset.create_pv(_pv_on("only-pv", "n0", sc="wffc"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c1", "1Gi", storage_class="wffc"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c2", "1Gi", storage_class="wffc"))
        s.clientset.create_pod(_pod_with_pvc("p1", "c1"))
        s.clientset.create_pod(_pod_with_pvc("p2", "c2"))
        s.run_until_idle()
        assert s.scheduled == 1  # second claim has no PV and no provisioner


class TestVolumeZone:
    def test_zone_mismatch_rejected(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).zone("z1").obj())
        s.clientset.create_node(
            make_node().name("n1").capacity({"cpu": "4", "pods": 10}).zone("z2").obj())
        pv = PersistentVolume.of("pv-z", "10Gi", storage_class="fast",
                                 labels={ZONE: "z2"})
        s.clientset.create_pv(pv)
        s.clientset.create_pvc(PersistentVolumeClaim.of(
            "c", "5Gi", storage_class="fast", volume_name="pv-z"))
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]


class TestNodeVolumeLimits:
    def test_csi_attach_limit(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_csi_node(CSINode(node_name="n0",
                                            driver_limits={"csi.x": 1}))
        s.clientset.create_storage_class(StorageClass(
            name="csi", provisioner="csi.x",
            volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        for i in range(2):
            s.clientset.create_pvc(PersistentVolumeClaim.of(
                f"c{i}", "1Gi", storage_class="csi"))
            s.clientset.create_pod(_pod_with_pvc(f"p{i}", f"c{i}"))
        s.run_until_idle()
        assert s.scheduled == 1  # limit 1 volume per node for driver csi.x


class TestVolumeRestrictions:
    def test_rwop_conflict(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_pv(_pv_on("pv-1", "n0", sc="fast"))
        pvc = PersistentVolumeClaim.of("c", "1Gi", storage_class="fast",
                                       volume_name="pv-1", access_modes=(RWOP,))
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p1", "c"))
        s.clientset.create_pod(_pod_with_pvc("p2", "c"))
        s.run_until_idle()
        assert s.scheduled == 1  # second user of the RWOP claim is rejected

    def test_rwop_conflict_resolvable_by_preemption(self):
        """Preemption dry-runs replay filter with add_pod/remove_pod; the
        RWOP refcount rides cycle state so evicting the current user clears
        the conflict (volumerestrictions AddPod/RemovePod)."""
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_pv(_pv_on("pv-1", "n0", sc="fast"))
        pvc = PersistentVolumeClaim.of("c", "1Gi", storage_class="fast",
                                       volume_name="pv-1", access_modes=(RWOP,))
        s.clientset.create_pvc(pvc)
        low = _pod_with_pvc("low", "c")
        low.priority = 1
        s.clientset.create_pod(low)
        s.run_until_idle()
        assert s.scheduled == 1
        high = _pod_with_pvc("high", "c")
        high.priority = 100
        s.clientset.create_pod(high)
        s.run_until_idle()
        bound = {p.name: p.node_name for p in s.clientset.pods.values() if p.node_name}
        assert bound.get("high") == "n0", f"high not scheduled via preemption: {bound}"


def test_pv_controller_binds_immediate_claims():
    """PV controller (core/pv_controller.py): IMMEDIATE-mode unbound claims
    bind to the smallest matching available PV as soon as both exist, which
    unblocks the scheduler's ERR_UNBOUND_IMMEDIATE rejection."""
    from kubernetes_tpu.api.storage import (
        PersistentVolume, PersistentVolumeClaim, StorageClass)
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.pv_controller import BIND_COMPLETED, PVController
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    from kubernetes_tpu.api.types import Volume

    cs = FakeClientset()
    ctrl = PVController(cs)
    sched = Scheduler(clientset=cs)
    cs.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
    cs.create_storage_class(StorageClass(name="std", volume_binding_mode="Immediate"))
    # both PVs first, then the claim — controller picks the smaller match
    cs.create_pv(PersistentVolume.of("big", "10Gi", storage_class="std"))
    cs.create_pv(PersistentVolume.of("small", "2Gi", storage_class="std"))
    pvc = PersistentVolumeClaim.of("data", "1Gi", storage_class="std")
    cs.create_pvc(pvc)
    assert pvc.volume_name == "small"
    assert pvc.annotations.get(BIND_COMPLETED) == "true"
    assert ctrl.binds == 1

    pod = make_pod().name("p").req({"cpu": "1"}).obj()
    pod.volumes.append(Volume(name="data", pvc_name="data"))
    cs.create_pod(pod)
    sched.run_until_idle()
    assert cs.bindings.get(pod.uid) == "n0"


def test_pv_controller_wffc_provisions_on_selected_node():
    """WaitForFirstConsumer: the scheduler's PreBind writes selected-node;
    the PV controller provisions a node-pinned PV and binds it
    (binder.go BindPodVolumes + external-provisioner contract)."""
    from kubernetes_tpu.api.storage import PersistentVolumeClaim, StorageClass
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.pv_controller import SELECTED_NODE, PVController
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    from kubernetes_tpu.api.types import Volume

    cs = FakeClientset()
    ctrl = PVController(cs)
    sched = Scheduler(clientset=cs)
    for i in range(3):
        cs.create_node(make_node().name(f"n{i}").capacity({"cpu": "8", "pods": 10}).obj())
    cs.create_storage_class(StorageClass(
        name="wffc", volume_binding_mode="WaitForFirstConsumer",
        provisioner="csi.example.com"))
    pvc = PersistentVolumeClaim.of("data", "1Gi", storage_class="wffc")
    cs.create_pvc(pvc)
    pod = make_pod().name("p").req({"cpu": "1"}).obj()
    pod.volumes.append(Volume(name="data", pvc_name="data"))
    cs.create_pod(pod)
    sched.run_until_idle()
    node = cs.bindings.get(pod.uid)
    assert node
    assert ctrl.provisions == 1
    assert pvc.volume_name.startswith("pvc-")
    assert pvc.annotations[SELECTED_NODE] == node
    pv = cs.pvs[pvc.volume_name]
    assert pv.csi_driver == "csi.example.com"
    # provisioned PV is pinned to the selected node
    assert pv.node_affinity is not None
    node_obj = cs.nodes[node]
    assert pv.node_affinity.matches(node_obj)


def _pv_cluster(cls, n_nodes=30, csi_limit=None):
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.scheduler import Scheduler as _S
    from kubernetes_tpu.models import TPUScheduler as _T
    from kubernetes_tpu.api.storage import CSINode
    from kubernetes_tpu.testing.wrappers import make_node

    cs = FakeClientset()
    kw = {"deterministic_ties": True} if cls is _S else {}
    sched = cls(clientset=cs, **kw)
    for i in range(n_nodes):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "8", "memory": "16Gi", "pods": 110}).obj())
        if csi_limit is not None:
            cs.create_csi_node(CSINode(
                node_name=f"n{i}", driver_limits={"csi.x": csi_limit}))
    return cs, sched


def _bound_pvc_pods(cs, n, driver=""):
    from kubernetes_tpu.api.storage import PersistentVolume, PersistentVolumeClaim
    from kubernetes_tpu.api.types import Volume
    from kubernetes_tpu.testing.wrappers import make_pod

    pods = []
    for i in range(n):
        pv = PersistentVolume.of(f"pv-{i}", "1Gi", access_modes=("ReadOnlyMany",),
                                 csi_driver=driver)
        pvc = PersistentVolumeClaim.of(f"pvc-{i}", "1Gi",
                                       access_modes=("ReadOnlyMany",))
        pv.claim_ref = pvc.key
        pvc.volume_name = pv.name
        cs.create_pv(pv)
        cs.create_pvc(pvc)
        p = make_pod().name(f"vp-{i}").req({"cpu": "100m", "memory": "64Mi"}).obj()
        p.volumes.append(Volume(name="data", pvc_name=f"pvc-{i}"))
        cs.create_pod(p)
        pods.append(p)
    return pods


def test_bound_pvc_pods_ride_device_and_match_host():
    """Bound claims with no node affinity / zone labels / limits impose no
    per-node constraint: such pods ride the device path with assignments
    identical to the host oracle."""
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.models import TPUScheduler

    cs_h, host = _pv_cluster(Scheduler)
    ph = _bound_pvc_pods(cs_h, 60)
    host.run_until_idle()
    cs_d, dev = _pv_cluster(TPUScheduler)
    pd = _bound_pvc_pods(cs_d, 60)
    dev.run_until_idle()
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd}
    assert hb == db
    assert dev.device_scheduled == 60
    assert dev.host_path_pods == 0


def test_csi_attach_limits_enforced_on_device():
    """The kernel's counted aux constraint (CSI attach limits,
    nodevolumelimits/csi.go): with limit 2 on 3 nodes, exactly 6 of 8 pods
    schedule, identical to the host oracle."""
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.models import TPUScheduler

    cs_h, host = _pv_cluster(Scheduler, n_nodes=3, csi_limit=2)
    ph = _bound_pvc_pods(cs_h, 8, driver="csi.x")
    host.run_until_idle()
    cs_d, dev = _pv_cluster(TPUScheduler, n_nodes=3, csi_limit=2)
    pd = _bound_pvc_pods(cs_d, 8, driver="csi.x")
    dev.run_until_idle()
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd}
    assert hb == db
    assert sum(1 for v in db.values() if v) == 6
    assert dev.device_scheduled >= 6


def test_shared_claim_pods_fall_back_to_host():
    """Two pods sharing one bound claim: the kernel's per-landing attach
    math would double-count, so the second pod must take the host path (and
    both schedule correctly)."""
    from kubernetes_tpu.api.storage import PersistentVolume, PersistentVolumeClaim
    from kubernetes_tpu.api.types import Volume
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing.wrappers import make_pod

    cs, dev = _pv_cluster(TPUScheduler, n_nodes=4, csi_limit=5)
    pv = PersistentVolume.of("shared-pv", "1Gi", access_modes=("ReadOnlyMany",),
                             csi_driver="csi.x")
    pvc = PersistentVolumeClaim.of("shared", "1Gi", access_modes=("ReadOnlyMany",))
    pv.claim_ref = pvc.key
    pvc.volume_name = pv.name
    cs.create_pv(pv)
    cs.create_pvc(pvc)
    pods = []
    for i in range(2):
        p = make_pod().name(f"sh-{i}").req({"cpu": "100m"}).obj()
        p.volumes.append(Volume(name="d", pvc_name="shared"))
        cs.create_pod(p)
        pods.append(p)
    dev.run_until_idle()
    assert all(cs.bindings.get(p.uid) for p in pods)
