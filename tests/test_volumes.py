"""Volume plugins: VolumeBinding, NodeVolumeLimits, VolumeZone,
VolumeRestrictions (reference plugins/volumebinding, nodevolumelimits/csi.go,
volumezone, volumerestrictions)."""

from kubernetes_tpu.api.labels import IN, Requirement
from kubernetes_tpu.api.storage import (
    RWO,
    RWOP,
    WAIT_FOR_FIRST_CONSUMER,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.api.types import NodeSelector, NodeSelectorTerm, Volume
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


def _pv_on(name, node_name, capacity="10Gi", sc="fast", **kw):
    return PersistentVolume.of(
        name, capacity, storage_class=sc,
        node_affinity=NodeSelector(terms=(NodeSelectorTerm(
            match_fields=(Requirement("metadata.name", IN, (node_name,)),)),)),
        **kw)


def _pod_with_pvc(name, pvc_name, cpu="100m"):
    p = make_pod().name(name).req({"cpu": cpu}).obj()
    p.volumes.append(Volume(name="data", pvc_name=pvc_name))
    return p


class TestVolumeBinding:
    def test_bound_pvc_node_affinity(self):
        s = Scheduler(deterministic_ties=True)
        for i in range(3):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        pv = _pv_on("pv-1", "n2")
        pvc = PersistentVolumeClaim.of("claim", "5Gi", storage_class="fast",
                                       volume_name="pv-1")
        s.clientset.create_pv(pv)
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "claim"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n2"]

    def test_unbound_immediate_is_unresolvable(self):
        s = Scheduler()
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(name="std", provisioner="x"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c", "1Gi", storage_class="std"))
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert s.scheduled == 0 and s.failures >= 1

    def test_wait_for_first_consumer_binds_pv(self):
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        s.clientset.create_pv(_pv_on("pv-a", "n1", sc="wffc"))
        pvc = PersistentVolumeClaim.of("c", "5Gi", storage_class="wffc")
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]
        assert pvc.volume_name == "pv-a"
        assert s.clientset.pvs["pv-a"].claim_ref == "default/c"

    def test_wffc_dynamic_provisioning(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", provisioner="csi.example.com",
            volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        pvc = PersistentVolumeClaim.of("c", "5Gi", storage_class="wffc")
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert s.scheduled == 1
        assert pvc.volume_name.startswith("pvc-")  # provisioned PV

    def test_two_claims_one_pv_conflict(self):
        """Second pod must not reuse the PV the first pod's claim assumed."""
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        s.clientset.create_pv(_pv_on("only-pv", "n0", sc="wffc"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c1", "1Gi", storage_class="wffc"))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c2", "1Gi", storage_class="wffc"))
        s.clientset.create_pod(_pod_with_pvc("p1", "c1"))
        s.clientset.create_pod(_pod_with_pvc("p2", "c2"))
        s.run_until_idle()
        assert s.scheduled == 1  # second claim has no PV and no provisioner


class TestVolumeZone:
    def test_zone_mismatch_rejected(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).zone("z1").obj())
        s.clientset.create_node(
            make_node().name("n1").capacity({"cpu": "4", "pods": 10}).zone("z2").obj())
        pv = PersistentVolume.of("pv-z", "10Gi", storage_class="fast",
                                 labels={ZONE: "z2"})
        s.clientset.create_pv(pv)
        s.clientset.create_pvc(PersistentVolumeClaim.of(
            "c", "5Gi", storage_class="fast", volume_name="pv-z"))
        s.clientset.create_pod(_pod_with_pvc("p", "c"))
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n1"]


class TestNodeVolumeLimits:
    def test_csi_attach_limit(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_csi_node(CSINode(node_name="n0",
                                            driver_limits={"csi.x": 1}))
        s.clientset.create_storage_class(StorageClass(
            name="csi", provisioner="csi.x",
            volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        for i in range(2):
            s.clientset.create_pvc(PersistentVolumeClaim.of(
                f"c{i}", "1Gi", storage_class="csi"))
            s.clientset.create_pod(_pod_with_pvc(f"p{i}", f"c{i}"))
        s.run_until_idle()
        assert s.scheduled == 1  # limit 1 volume per node for driver csi.x


class TestVolumeRestrictions:
    def test_rwop_conflict(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_pv(_pv_on("pv-1", "n0", sc="fast"))
        pvc = PersistentVolumeClaim.of("c", "1Gi", storage_class="fast",
                                       volume_name="pv-1", access_modes=(RWOP,))
        s.clientset.create_pvc(pvc)
        s.clientset.create_pod(_pod_with_pvc("p1", "c"))
        s.clientset.create_pod(_pod_with_pvc("p2", "c"))
        s.run_until_idle()
        assert s.scheduled == 1  # second user of the RWOP claim is rejected

    def test_rwop_conflict_resolvable_by_preemption(self):
        """Preemption dry-runs replay filter with add_pod/remove_pod; the
        RWOP refcount rides cycle state so evicting the current user clears
        the conflict (volumerestrictions AddPod/RemovePod)."""
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(make_node().name("n0").capacity({"cpu": "8", "pods": 10}).obj())
        s.clientset.create_pv(_pv_on("pv-1", "n0", sc="fast"))
        pvc = PersistentVolumeClaim.of("c", "1Gi", storage_class="fast",
                                       volume_name="pv-1", access_modes=(RWOP,))
        s.clientset.create_pvc(pvc)
        low = _pod_with_pvc("low", "c")
        low.priority = 1
        s.clientset.create_pod(low)
        s.run_until_idle()
        assert s.scheduled == 1
        high = _pod_with_pvc("high", "c")
        high.priority = 100
        s.clientset.create_pod(high)
        s.run_until_idle()
        bound = {p.name: p.node_name for p in s.clientset.pods.values() if p.node_name}
        assert bound.get("high") == "n0", f"high not scheduled via preemption: {bound}"
