"""Auxiliary subsystems: cache debugger, leader election, scheduler server,
extra plugins (SURVEY.md §5, §2.3 tail)."""

import json
import urllib.request

from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.debugger import CacheDebugger
from kubernetes_tpu.core.leaderelection import LeaderElector, LeaseStore
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.core.server import SchedulerServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _basic_sched():
    s = Scheduler()
    s.clientset.create_node(
        make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
    s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
    s.run_until_idle()
    return s


class TestCacheDebugger:
    def test_dump_and_compare_clean(self):
        s = _basic_sched()
        d = CacheDebugger(s)
        out = d.dump()
        assert "n0" in out and "Queue:" in out
        assert d.compare() == []

    def test_compare_detects_divergence(self):
        s = _basic_sched()
        # sabotage: drop the node from the cache behind the scheduler's back
        s.cache.remove_node("n0")
        d = CacheDebugger(s)
        problems = d.compare()
        assert any("n0" in p for p in problems)


class TestLeaderElection:
    def test_single_candidate_acquires(self):
        store = LeaseStore()
        t = [0.0]
        e = LeaderElector(store, "a", now=lambda: t[0])
        assert e.tick() and e.is_leader()

    def test_failover_after_expiry(self):
        store = LeaseStore()
        t = [0.0]
        a = LeaderElector(store, "a", now=lambda: t[0])
        b = LeaderElector(store, "b", now=lambda: t[0])
        assert a.tick()
        assert not b.tick()  # a holds the lease
        t[0] = 20.0          # a missed renewals past leaseDuration (15s)
        assert b.tick() and b.is_leader()
        assert not a.tick()  # a observes the takeover and steps down
        assert not a.is_leader()

    def test_voluntary_release(self):
        store = LeaseStore()
        a = LeaderElector(store, "a")
        b = LeaderElector(store, "b")
        a.tick()
        a.release()
        assert b.tick()


class TestSchedulerServer:
    def test_endpoints(self):
        s = _basic_sched()
        srv = SchedulerServer(s)
        port = srv.serve()
        try:
            def get(path):
                with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                    return r.status, r.read().decode()

            assert get("/healthz")[0] == 200
            assert get("/readyz")[0] == 200
            status, body = get("/metrics")
            assert status == 200 and "scheduler_schedule_attempts_total" in body
            status, body = get("/debug/cache")
            assert status == 200 and "n0" in body
            status, body = get("/debug/comparer")
            assert status == 200 and json.loads(body) == []
        finally:
            srv.shutdown()

    def test_run_cycles_requires_leadership(self):
        store = LeaseStore()
        s1 = Scheduler()
        srv1 = SchedulerServer(s1, identity="a", lease_store=store, leader_elect=True)
        s2 = Scheduler()
        srv2 = SchedulerServer(s2, identity="b", lease_store=store, leader_elect=True)
        for srv in (srv1, srv2):
            srv.scheduler.clientset.create_node(
                make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
            srv.scheduler.clientset.create_pod(
                make_pod().name("p").req({"cpu": "1"}).obj())
        srv1.run_cycles()
        srv2.run_cycles()
        assert s1.scheduled == 1   # leader scheduled
        assert s2.scheduled == 0   # standby did nothing


class TestExtraPlugins:
    def test_node_declared_features(self):
        cfg = SchedulerConfiguration(profiles=[ProfileConfig(
            plugins=PluginSet(enabled=(("NodeDeclaredFeatures", 0),)))])
        s = Scheduler(config=cfg, deterministic_ties=True)
        n_plain = make_node().name("plain").capacity({"cpu": "4", "pods": 10}).obj()
        n_feat = make_node().name("featured").capacity({"cpu": "4", "pods": 10}).obj()
        n_feat.declared_features = {"fast-net": True}
        s.clientset.create_node(n_plain)
        s.clientset.create_node(n_feat)
        p = make_pod().name("p").req({"cpu": "1"}).obj()
        p.annotations["features.k8s.io/required"] = "fast-net"
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["featured"]

    def test_deferred_pod_scheduling(self):
        t = [100.0]
        cfg = SchedulerConfiguration(profiles=[ProfileConfig(
            plugins=PluginSet(enabled=(("DeferredPodScheduling", 0),)),
            plugin_config={"DeferredPodScheduling": {"now": lambda: t[0]}})])
        s = Scheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        p = make_pod().name("deferred").req({"cpu": "1"}).obj()
        p.annotations["scheduling.k8s.io/defer-until"] = "200.0"
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 0  # gated
        t[0] = 250.0
        updated = p  # annotation unchanged; deadline passed
        s.clientset.update_pod(updated)
        s.run_until_idle()
        assert s.scheduled == 1


def test_remote_clientset_equivalence_with_latency():
    """The watch-seam transport (core/remote.py): scheduling against a
    1ms-RTT apiserver thread with the async dispatcher produces the SAME
    assignments as the in-process clientset, with watch events crossing
    threads through the reflector inbox."""
    from kubernetes_tpu.core import FakeClientset, Scheduler
    from kubernetes_tpu.core.config import SchedulerConfiguration
    from kubernetes_tpu.core.remote import RemoteClientset
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    def load(cs):
        for i in range(20):
            cs.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
                           .zone(f"z{i % 4}").obj())
        proto = make_pod().name("proto").req({"cpu": "500m"}).obj()
        pods = [proto.clone_from_template(f"p{i}") for i in range(80)]
        for p in pods:
            cs.create_pod(p)
        return pods

    cs_h = FakeClientset()
    host = Scheduler(clientset=cs_h, deterministic_ties=True)
    ph = load(cs_h)
    host.run_until_idle()

    cs_r = RemoteClientset(rtt=0.001)
    cfg = SchedulerConfiguration(async_dispatch_threads=True)
    dev = TPUScheduler(clientset=cs_r, config=cfg)
    pr = load(cs_r)
    import time
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and dev.scheduled < 80:
        dev.run_until_idle()
        time.sleep(0.002)
    dev.api_dispatcher.flush()
    dev.run_until_idle()
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    rb = {p.name: cs_r.bindings.get(p.uid) for p in pr}
    assert hb == rb
    assert cs_r.calls >= 180  # every write crossed the transport
    cs_r.close()


def test_scheduler_binary_once_mode(tmp_path):
    """The cmd/kube-scheduler analogue (python -m kubernetes_tpu): bootstrap
    a cluster manifest, serve endpoints, drain the queue, exit cleanly."""
    import os
    import subprocess
    import sys

    manifest = tmp_path / "cluster.yaml"
    manifest.write_text(
        "nodes:\n- {count: 6, cpu: 8, memory: 32Gi, pods: 110, zones: 2}\n"
        "pods:\n- {count: 12, cpu: 250m}\n")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in the child
    out = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu", "--cluster", str(manifest),
         "--port", "0", "--once", "--platform", "cpu"],
        capture_output=True, text=True, timeout=180, cwd=repo_root, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "scheduled=12 failures=0" in out.stdout
