"""Tier-1 gate + self-tests for the project invariant analyzer
(kubernetes_tpu/analysis/, docs/ANALYSIS.md).

Three layers:

- fixture corpus: every checker must flag its known-bad snippets (the
  recorded incident patterns, seeded) and pass its known-good twins;
- the tree gate: `analyze()` over the real package reports zero findings
  and zero stale allowlist entries — this is what makes the analyzer a
  floor under every future PR;
- the CLI contract: `python -m kubernetes_tpu.analysis` exits 0 on the
  tree and nonzero (with --json detail) on a tree seeded with violations.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

import pytest

from kubernetes_tpu.analysis import (ALLOWLIST, Allow, all_checkers, analyze,
                                     check_source, checker_by_id,
                                     validate_allowlist)
from kubernetes_tpu.analysis.metrics_discipline import (Declaration,
                                                        MetricsDisciplineChecker)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# fixture corpus: index-dtype
# ---------------------------------------------------------------------------


class TestIndexDtypeFixtures:
    def test_flags_bad_producers(self):
        bad = textwrap.dedent("""
            import jax.numpy as jnp
            def f(x, idx, dirty):
                a = jnp.arange(5)                     # bare arange
                b = jnp.argmax(x, axis=0)             # uncast argmax
                c = jnp.asarray(idx)                  # index vec, no dtype
                d = jnp.asarray(sorted(dirty))        # ditto through sorted
                return a, b, c, d
        """)
        fs = check_source(checker_by_id("index-dtype"), bad)
        assert _rules(fs) == ["arange-dtype", "argmax-cast",
                              "asarray-index-dtype"]
        assert len(fs) == 4
        assert {f.line for f in fs} == {4, 5, 6, 7}

    def test_passes_pinned_producers(self):
        good = textwrap.dedent("""
            import jax.numpy as jnp
            def f(x, idx, dirty):
                a = jnp.arange(5, dtype=jnp.int32)
                b = jnp.argmax(x, axis=0).astype(jnp.int32)
                c = jnp.asarray(idx, jnp.int32)
                d = jnp.asarray(sorted(dirty), dtype=jnp.int32)
                e = jnp.asarray(x)     # not an index-named vector: exempt
                return a, b, c, d, e
        """)
        assert check_source(checker_by_id("index-dtype"), good) == []

    def test_string_parens_do_not_confuse_the_scan(self):
        """The old regex guard's _call_text was string-literal-naive: a ')'
        inside a string ended its paren matching. The AST checker must see
        through it both ways."""
        tricky_good = textwrap.dedent("""
            import jax.numpy as jnp
            def f():
                msg = "jnp.arange(8)"     # a string, not a call
                return jnp.arange(8, dtype=jnp.int32), msg
        """)
        assert check_source(checker_by_id("index-dtype"), tricky_good) == []
        tricky_bad = textwrap.dedent("""
            import jax.numpy as jnp
            def f():
                note = ") dtype= :)"      # old parser would see this text
                return jnp.arange(8), note
        """)
        fs = check_source(checker_by_id("index-dtype"), tricky_bad)
        assert _rules(fs) == ["arange-dtype"]

    def test_argmax_cast_is_statement_scoped(self):
        mixed = textwrap.dedent("""
            import jax.numpy as jnp
            def f(x):
                i = jnp.argmax(x)          # bad: cast happens a line later
                i = i.astype(jnp.int32)
                return i
        """)
        fs = check_source(checker_by_id("index-dtype"), mixed)
        assert _rules(fs) == ["argmax-cast"]


# ---------------------------------------------------------------------------
# fixture corpus: lock-discipline
# ---------------------------------------------------------------------------


BAD_APISERVER = textwrap.dedent("""
    import threading
    class Server:
        def do_POST(self):                       # no write lock, no delegate
            body = self._read_body()
            self.store.pods[body["uid"]] = body
        def _broadcast(self, kind, event):
            with self._lock:
                for q in self._watchers[kind]:   # fanout BEFORE the append
                    q.put(event)
                self.persistence.append(event)
        def _wal_status(self, rec):
            self.persistence.append(rec)         # append outside any lock
        def do_DELETE(self):
            with self._write_lock:
                body = self._read_body()         # blocking read under lock
""")

GOOD_APISERVER = textwrap.dedent("""
    import threading
    class Server:
        def do_POST(self):
            body = self._read_body()             # read OUTSIDE the lock
            with self._write_lock:
                self.store.pods[body["uid"]] = body
        def do_PUT(self):
            self.upsert(self._read_body())       # delegate serializes
        def upsert(self, rec):
            with self._write_lock:
                self.leases[rec["name"]] = rec
        def _broadcast(self, kind, event):
            with self._lock:
                self.persistence.append(event)   # durable BEFORE fanout
                for q in self._watchers[kind]:
                    q.put(event)
""")


class TestLockDisciplineFixtures:
    def test_flags_all_four_rules(self):
        fs = check_source(checker_by_id("lock-discipline"), BAD_APISERVER)
        assert _rules(fs) == ["no-blocking-read-under-lock",
                              "verb-write-lock", "wal-before-fanout",
                              "wal-under-broadcast-lock"]

    def test_passes_disciplined_server(self):
        assert check_source(checker_by_id("lock-discipline"),
                            GOOD_APISERVER) == []

    def test_directly_nested_withs_hold_both_locks(self):
        """Regression (PR 7 review): a `with` as the DIRECT first statement
        of another `with`'s body must inherit the outer lock — correct
        code like write_lock-then-broadcast-lock used to false-positive."""
        nested_good = textwrap.dedent("""
            class Server:
                def commit(self, event):
                    with self._write_lock:
                        with self._lock:
                            self.persistence.append(event)
                            for q in self._watchers["pods"]:
                                q.put(event)
        """)
        assert check_source(checker_by_id("lock-discipline"),
                            nested_good) == []

    def test_duplicate_function_names_each_get_scanned(self):
        """Regression (PR 7 review): two defs sharing a name (apiserver.py
        has upsert_lease on BOTH APIServer and HTTPClientset) must each be
        analyzed — the buggy version kept only the last one, silently
        skipping the server-side locking."""
        dup = textwrap.dedent("""
            class Server:
                def upsert_lease(self, rec):
                    self.persistence.append(rec)     # VIOLATION: no lock
            class Client:
                def upsert_lease(self, rec):
                    return self._call("PUT", rec)    # clean REST wrapper
        """)
        fs = check_source(checker_by_id("lock-discipline"), dup)
        assert _rules(fs) == ["wal-under-broadcast-lock"]
        assert len(fs) == 1 and fs[0].line == 4

    def test_scope_is_apiserver_and_wal(self):
        c = checker_by_id("lock-discipline")
        assert c.applies_to("core/apiserver.py")
        assert c.applies_to("core/wal.py")
        assert not c.applies_to("core/scheduler.py")

    def test_flags_metrics_render_under_write_lock(self):
        """PR 8 rule: /metrics exposition must never hold the write lock —
        a scrape serialized against the write plane stalls every bind for
        the whole render (ROADMAP: /metrics/resources contention)."""
        bad = textwrap.dedent("""
            class Server:
                def do_GET(self):
                    with self._write_lock:
                        body = self.expose_metrics()
                def expose_metrics(self):
                    return ""
        """)
        fs = check_source(checker_by_id("lock-discipline"), bad)
        assert "no-render-under-write-lock" in _rules(fs)

    def test_render_outside_write_lock_is_clean(self):
        good = textwrap.dedent("""
            class Server:
                def do_GET(self):
                    body = self.expose_metrics()   # no lock held: fine
                    with self._lock:
                        n = len(self._watchers)    # broadcast lock ≠ write
                def expose_metrics(self):
                    return ""
        """)
        fs = check_source(checker_by_id("lock-discipline"), good)
        assert "no-render-under-write-lock" not in _rules(fs)


# ---------------------------------------------------------------------------
# fixture corpus: lock-discipline, replication rules (PR 9)
# ---------------------------------------------------------------------------


BAD_REPLICATION = textwrap.dedent("""
    class Follower:
        def apply_frame(self, rec):                  # no write lock taken
            with self._lock:
                for q in self._watchers["pods"]:     # fanout BEFORE append
                    q.put(rec)
                self.persistence.append(rec)
        def _wal_status(self, rec):
            self._repl_append(rec)                   # frame append, no lock
        def _ship(self, st):
            with self._lock:
                self.wfile.write(b"x")               # send under lock
                st.sock.sendall(b"y")                # ditto
""")

GOOD_REPLICATION = textwrap.dedent("""
    class Follower:
        def apply_frame(self, rec):
            with self._write_lock:
                with self._lock:
                    self.persistence.append(rec)     # durable FIRST
                    for q in self._watchers["pods"]:
                        q.put(rec)
        def _wal_status(self, rec):
            with self._lock:
                self._repl_append(rec)               # caller holds the lock
        def _ship(self, st):
            with self._lock:
                frames = list(st.pending)            # snapshot under lock
            for data in frames:
                self.wfile.write(data)               # send OUTSIDE any lock
""")


class TestReplicationLockFixtures:
    def test_flags_replication_violations(self):
        fs = check_source(checker_by_id("lock-discipline"), BAD_REPLICATION)
        assert _rules(fs) == ["no-blocking-send-under-lock",
                              "repl-apply-write-lock",
                              "wal-before-fanout",
                              "wal-under-broadcast-lock"]
        # both send sites (wfile.write AND sendall) are individually flagged
        assert sum(1 for f in fs
                   if f.rule == "no-blocking-send-under-lock") == 2

    def test_passes_disciplined_follower(self):
        assert check_source(checker_by_id("lock-discipline"),
                            GOOD_REPLICATION) == []

    def test_repl_append_inside_primitive_is_exempt(self):
        """The frame-append primitive OWNS the raw persistence.append; its
        contract (caller holds the broadcast lock) is enforced at call
        sites, not inside it."""
        primitive = textwrap.dedent("""
            class Server:
                def _repl_append(self, rec):
                    self.persistence.append(rec)     # exempt: the primitive
                def _broadcast(self, event):
                    with self._lock:
                        self._repl_append(event)     # call site: locked
        """)
        assert check_source(checker_by_id("lock-discipline"),
                            primitive) == []

    def test_scope_covers_replication_module(self):
        c = checker_by_id("lock-discipline")
        assert c.applies_to("replication/follower.py")
        assert c.applies_to("kubernetes_tpu/replication/follower.py")
        assert not c.applies_to("core/scheduler.py")


# ---------------------------------------------------------------------------
# fixture corpus: lock-discipline — watch-cache read plane (PR 10)
# ---------------------------------------------------------------------------


BAD_WATCHCACHE = textwrap.dedent("""
    class Server:
        def do_summary(self):
            with self._write_lock:                       # read plane must
                return self.watch_cache["pods"].read_summary()  # not be here
        def do_list(self):
            with self._write_lock:
                return self.watch_cache["pods"].list_wire()
        def _broadcast(self, event):
            with self._lock:
                self.watch_cache["pods"].note_event(1, "ADDED", event)
                self._repl_append(event)                 # append AFTER cache
        def _recover_seed(self, objs):
            self.watch_cache["pods"].reinstall(objs, 0)  # outside the lock
""")

GOOD_WATCHCACHE = textwrap.dedent("""
    class Server:
        def do_summary(self):
            return self.watch_cache["pods"].read_summary()   # own lock only
        def do_resources(self):
            return self.watch_cache["pods"].render_resources()
        def _broadcast(self, event):
            with self._lock:
                self._repl_append(event)                 # durable first...
                self._fan_event("pods", event, b"")      # ...then cache+fan
        def _fan_event(self, kind, event, data):
            self.watch_cache[kind].note_event(1, "ADDED", event)  # primitive
            for w in self._watchers[kind]:
                w.q.put(data)
""")


class TestWatchCacheLockFixtures:
    def test_flags_watchcache_violations(self):
        fs = check_source(checker_by_id("lock-discipline"), BAD_WATCHCACHE)
        assert _rules(fs) == ["no-read-serving-under-write-lock"]
        # two reads under the write lock + the mutation-before-append +
        # the unlocked reinstall are each individually flagged
        assert len(fs) == 4

    def test_passes_disciplined_watchcache(self):
        """The fanout primitive owns the raw note_event (caller-holds-lock
        contract, enforced at its call sites) — the real apiserver shape
        passes clean."""
        assert check_source(checker_by_id("lock-discipline"),
                            GOOD_WATCHCACHE) == []

    def test_fan_event_call_outside_lock_flagged(self):
        bad = textwrap.dedent("""
            class Server:
                def _broadcast(self, event):
                    with self._lock:
                        self._repl_append(event)
                    self._fan_event("pods", event, b"")  # lock released!
                def _fan_event(self, kind, event, data):
                    self.watch_cache[kind].note_event(1, "ADDED", event)
        """)
        fs = check_source(checker_by_id("lock-discipline"), bad)
        assert "no-read-serving-under-write-lock" in _rules(fs)

    def test_scope_covers_watchcache_module(self):
        c = checker_by_id("lock-discipline")
        assert c.applies_to("core/watchcache.py")
        assert c.applies_to("kubernetes_tpu/core/watchcache.py")


# ---------------------------------------------------------------------------
# fixture corpus: lock-discipline — paged-LIST continuation path (PR 11)
# ---------------------------------------------------------------------------


BAD_CONTINUATION = textwrap.dedent("""
    class Server:
        def serve_page(self, limit, last_key):
            with self._write_lock:                        # continuation
                objs = self.watch_cache["pods"].list_page(limit, last_key)
                token = mint_continue(1, last_key, "e")   # minted under it
            return objs, token
""")

GOOD_CONTINUATION = textwrap.dedent("""
    class Server:
        def serve_page(self, limit, last_key):
            objs = self.watch_cache["pods"].list_page(limit, last_key)
            token = mint_continue(1, last_key, "e")       # lock-free mint
            return objs, token
""")


class TestContinuationLockFixtures:
    def test_flags_page_serving_and_minting_under_write_lock(self):
        """The continuation-serving path is a READ: a 50k-node paged list
        serialized against the bind plane stalls it once per page."""
        fs = check_source(checker_by_id("lock-discipline"), BAD_CONTINUATION)
        assert _rules(fs) == ["no-read-serving-under-write-lock"]
        assert len(fs) == 2   # the page serve AND the token mint

    def test_passes_lock_free_continuation(self):
        assert check_source(checker_by_id("lock-discipline"),
                            GOOD_CONTINUATION) == []

    def test_scope_covers_hollow_plane(self):
        c = checker_by_id("lock-discipline")
        assert c.applies_to("hollow/plane.py")
        assert c.applies_to("kubernetes_tpu/hollow/plane.py")


# ---------------------------------------------------------------------------
# fixture corpus: jit-purity
# ---------------------------------------------------------------------------


class TestJitPurityFixtures:
    def test_flags_impure_jit_functions(self):
        bad = textwrap.dedent("""
            import jax, time
            from functools import partial
            CALLS = 0
            @partial(jax.jit, static_argnames=("k",))
            def kernel(x, k):
                global CALLS
                CALLS += 1                 # baked in at trace time
                print("tracing", x)        # host effect under trace
                t = time.perf_counter()    # host clock under trace
                return x * k
            def build(state, cfg):
                def step(s):
                    cfg.calls = 1          # attr mutation under trace
                    return s + 1
                return jax.jit(step)
        """)
        fs = check_source(checker_by_id("jit-purity"), bad)
        assert _rules(fs) == ["no-attr-assign", "no-global-mutation",
                              "no-impure-call"]
        assert sum(f.rule == "no-impure-call" for f in fs) == 2

    def test_passes_pure_kernels(self):
        good = textwrap.dedent("""
            import jax
            import jax.numpy as jnp
            from functools import partial
            @partial(jax.jit, static_argnames=("k",))
            def kernel(x, k):
                jax.debug.print("ok {}", x)   # traced debugging is fine
                return jnp.cumsum(x) * k
            def host_driver(state):
                state.calls = 1               # host code may mutate freely
                import time
                return time.perf_counter()
        """)
        assert check_source(checker_by_id("jit-purity"), good) == []

    def test_transitive_helpers_are_traced_too(self):
        """A helper called from a jitted function is traced like its
        caller — impurity there is the same bug one stack frame down."""
        bad = textwrap.dedent("""
            import jax
            @jax.jit
            def kernel(x):
                return _helper(x)
            def _helper(x):
                print("traced!")       # impure, reached through kernel
                return x + 1
            def _host_only(x):
                print("fine")          # never reaches a jit
                return x
        """)
        fs = check_source(checker_by_id("jit-purity"), bad)
        assert [f.rule for f in fs] == ["no-impure-call"]
        assert fs[0].line == 7

    def test_flags_donated_buffer_reuse(self):
        bad = textwrap.dedent("""
            import jax
            def session(carry, feats):
                step = jax.jit(lambda c, f: c, donate_argnums=(0,))
                step = jax.jit(_impl, donate_argnums=(0,))
                out = step(carry, feats)
                return out + carry.total     # carry's buffer was donated
            def _impl(c, f):
                return c
        """)
        fs = check_source(checker_by_id("jit-purity"), bad)
        assert any(f.rule == "donated-buffer-reuse" for f in fs)

    def test_donation_rebind_is_clean(self):
        good = textwrap.dedent("""
            import jax
            def session(carry, feats):
                step = jax.jit(_impl, donate_argnums=(0,))
                carry = step(carry, feats)   # rebound: later reads see new
                return carry.total
            def _impl(c, f):
                return c
        """)
        assert check_source(checker_by_id("jit-purity"), good) == []


# ---------------------------------------------------------------------------
# fixture corpus: thread-hygiene
# ---------------------------------------------------------------------------


class TestThreadHygieneFixtures:
    def test_flags_unjoined_nondaemon_threads(self):
        bad = textwrap.dedent("""
            import threading
            class Pump:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
                    threading.Thread(target=self._aux).start()
        """)
        fs = check_source(checker_by_id("thread-hygiene"), bad)
        assert len(fs) == 2
        assert _rules(fs) == ["daemon-or-joined"]

    def test_passes_daemon_joined_and_pooled(self):
        good = textwrap.dedent("""
            import threading
            class Pump:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
                    threading.Thread(target=self._aux, daemon=True).start()
                    w = threading.Thread(target=self._w)
                    self._threads.append(w)
                    self._threads.append(threading.Thread(target=self._v))
                def close(self):
                    self._t.join(timeout=2)
                    for t in self._threads:
                        t.join(timeout=2)
        """)
        assert check_source(checker_by_id("thread-hygiene"), good) == []


# ---------------------------------------------------------------------------
# fixture corpus: metrics-discipline
# ---------------------------------------------------------------------------


DECLS = {
    "hits": Declaration("hits", "Counter", "scheduler_hits_total",
                        ("result",), 10),
    "depth": Declaration("depth", "Gauge", "scheduler_depth", (), 11),
    "latency": Declaration("latency", "Histogram", "scheduler_latency",
                           ("kind",), 12),
}


class TestMetricsDisciplineFixtures:
    def _check(self, src):
        return check_source(MetricsDisciplineChecker(declarations=DECLS), src)

    def test_flags_undeclared_mismatch_and_arity(self):
        bad = textwrap.dedent("""
            class S:
                def go(self):
                    self.metrics.misses.inc()              # undeclared
                    self.metrics.depth.inc()               # Gauge via inc
                    self.metrics.hits.inc("ok", "extra")   # arity 2 != 1
                    self.metrics.latency.observe(0.5)      # arity 1 != 2
        """)
        fs = self._check(bad)
        assert _rules(fs) == ["label-arity", "metric-verb-mismatch",
                              "undeclared-metric"]
        assert sum(f.rule == "label-arity" for f in fs) == 2

    def test_resolves_local_aliases(self):
        bad = textwrap.dedent("""
            class S:
                def go(self):
                    m = self.metrics
                    m.misses.inc()                    # undeclared via alias
                    h = self.metrics.latency
                    h.observe(0.5)                    # arity via alias
        """)
        fs = self._check(bad)
        assert _rules(fs) == ["label-arity", "undeclared-metric"]

    def test_passes_disciplined_usage(self):
        good = textwrap.dedent("""
            class S:
                def go(self, n):
                    self.metrics.hits.inc("ok")
                    self.metrics.hits.inc("ok", value=n)
                    self.metrics.depth.set(float(n))
                    h = self.metrics.latency
                    h.observe(0.5, "bind")
                    self.other.inc("unrelated", "object", "calls")
        """)
        assert self._check(good) == []

    def test_label_cardinality_bound(self):
        over = textwrap.dedent("""
            class SchedulerMetrics:
                def __init__(self):
                    r = self.registry.register
                    self.wide = r(Counter(
                        "scheduler_wide_total", "too many dims.",
                        ("a", "b", "c", "d")))
        """)
        fs = check_source(MetricsDisciplineChecker(declarations=DECLS), over,
                          path="core/metrics.py")
        assert _rules(fs) == ["label-cardinality"]

    def test_real_declarations_parse(self):
        from kubernetes_tpu.analysis.metrics_discipline import (
            parse_declarations)
        from kubernetes_tpu.analysis.base import PKG_ROOT
        decls = parse_declarations((PKG_ROOT / "core/metrics.py").read_text())
        assert len(decls) > 50
        assert decls["schedule_attempts"].kind == "Counter"
        assert decls["schedule_attempts"].labels == ("result", "profile")
        assert decls["pending_pods"].kind == "Gauge"
        assert all(d.labels is None or len(d.labels) <= 3
                   for d in decls.values())


# ---------------------------------------------------------------------------
# fixture corpus: span-discipline (PR 8 telemetry contract)
# ---------------------------------------------------------------------------


class TestSpanDisciplineFixtures:
    def test_flags_unended_and_unguarded_starts(self):
        bad = textwrap.dedent("""
            class S:
                def leak(self, pod):
                    sp = self.tracer.start_span("api.bind", self.ctx)
                    self.commit(pod)               # never ended: leaks
                def unguarded(self, pod):
                    sp = self.tracer.start_span("api.bind", self.ctx)
                    self.commit(pod)               # raises -> end skipped
                    self.tracer.end(sp)
        """)
        fs = check_source(checker_by_id("span-discipline"), bad)
        assert _rules(fs) == ["span-end-unguarded", "span-unended"]

    def test_passes_with_scoped_and_finally_ended_spans(self):
        good = textwrap.dedent("""
            class S:
                def scoped(self, pod):
                    with self.tracer.span("api.bind", self.ctx):
                        self.commit(pod)
                def guarded(self, pod):
                    sp = self.tracer.start_span("api.bind", self.ctx)
                    try:
                        self.commit(pod)
                    finally:
                        self.tracer.end(sp)
                def method_form(self, pod):
                    sp = self.tracer.start_span("api.bind", self.ctx)
                    try:
                        self.commit(pod)
                    finally:
                        sp.end()
                def retro(self, pod):
                    self.tracer.record("api.bind", self.ctx, 0.1)  # complete
        """)
        assert check_source(checker_by_id("span-discipline"), good) == []

    def test_flags_span_and_metric_calls_in_jit_reachable_code(self):
        """Composes with the jit-purity walker: a tracer/metrics call one
        helper below a jitted kernel is the same trace-time-bake bug."""
        bad = textwrap.dedent("""
            import jax
            @jax.jit
            def kernel(x, self):
                return _helper(x, self)
            def _helper(x, self):
                self.tracer.record("device.wait", self.ctx, 0.1)
                self.metrics.batch_size.observe(4)
                return x
        """)
        fs = check_source(checker_by_id("span-discipline"), bad)
        assert _rules(fs) == ["span-in-jit"]
        assert len(fs) == 2

    def test_host_side_span_and_metric_calls_are_clean(self):
        good = textwrap.dedent("""
            import jax
            @jax.jit
            def kernel(x):
                return x + 1
            def host_commit(self, batch):
                self.tracer.record("host.commit", self.ctx, 0.1)
                self.metrics.batch_size.observe(len(batch))
                return kernel(batch)
        """)
        assert check_source(checker_by_id("span-discipline"), good) == []


# ---------------------------------------------------------------------------
# fixture corpus: hint-freshness
# ---------------------------------------------------------------------------


class TestHintFreshnessFixtures:
    """Cache NodeInfo-accounting mutations must be on the score-hint
    invalidation call graph (ISSUE 12: a mutation the journal/fences never
    see would silently stale a live hint)."""

    def test_flags_unfenced_mutation(self):
        bad = textwrap.dedent("""
            class S:
                def sneaky_rebalance(self, pod):
                    # moves accounting with no journal record, no fence
                    self.cache.forget_pod(pod)
                    self.cache.assume_pod(pod)
        """)
        fs = check_source(checker_by_id("hint-freshness"), bad)
        assert _rules(fs) == ["accounting-outside-invalidation-graph"]
        assert len(fs) == 2

    def test_passes_journaled_mutation(self):
        good = textwrap.dedent("""
            class S:
                def on_event(self, kind, new):
                    self._record_pod_event(kind, None, new)
                    self.cache.add_pod(new)
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_passes_fence_counter_bump(self):
        good = textwrap.dedent("""
            class S:
                def unwind(self, pod):
                    self.state_unwinds += 1
                    self.cache.forget_pod(pod)
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_passes_hint_cache_call(self):
        good = textwrap.dedent("""
            class S:
                def conflict(self, pod, node):
                    self.cache.forget_pod(pod)
                    self._hints.note_conflict(node)
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_caller_direction_credits_the_slice(self):
        """The process_one → scheduling_cycle shape: the assume lives one
        frame below the attempt-counter bump — the SLICE has the sink."""
        good = textwrap.dedent("""
            class S:
                def process_one(self, qpi):
                    self.attempts += 1
                    self.scheduling_cycle(qpi)
                def scheduling_cycle(self, qpi):
                    self.cache.assume_pod(qpi.pod)
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_callee_direction_credits_the_slice(self):
        good = textwrap.dedent("""
            class S:
                def commit(self, pod):
                    self.cache.assume_pod(pod)
                    self.note_it()
                def note_it(self):
                    self.attempts += 1
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_snapshot_whatif_mutations_exempt(self):
        """snapshot.assume_pod is a gang-simulation what-if, not cache
        accounting — matched on the `cache` base, so exempt."""
        good = textwrap.dedent("""
            class S:
                def simulate(self, pod):
                    self.snapshot.assume_pod(pod)
                    self.snapshot.forget_pod(pod)
        """)
        assert check_source(checker_by_id("hint-freshness"), good) == []

    def test_unrelated_caller_does_not_credit(self):
        """A sink-holding function that never reaches the mutator must not
        launder it."""
        bad = textwrap.dedent("""
            class S:
                def elsewhere(self):
                    self.attempts += 1
                def sneaky(self, pod):
                    self.cache.forget_pod(pod)
        """)
        fs = check_source(checker_by_id("hint-freshness"), bad)
        assert len(fs) == 1 and fs[0].line == 6

    def test_duplicate_method_names_both_scanned(self):
        """lock-discipline's lesson, re-learned here in review: a Handle
        delegate sharing a Scheduler method's NAME must not shadow the
        real def — the SECOND def's unfenced mutation is a finding."""
        bad = textwrap.dedent("""
            class Handle:
                def reject_waiting_pod(self, uid):
                    return self._scheduler.lookup(uid)
            class S:
                def reject_waiting_pod(self, uid):
                    self.cache.forget_pod(uid)   # unfenced, 2nd def
        """)
        fs = check_source(checker_by_id("hint-freshness"), bad)
        assert len(fs) == 1 and fs[0].line == 7


# ---------------------------------------------------------------------------
# fixture corpus: shed-discipline (overload plane, PR 14)
# ---------------------------------------------------------------------------


BAD_SHED = textwrap.dedent("""
    class Handler:
        def do_POST(self):
            ticket = None
            with server._write_lock:
                # admission under the very lock it exists to protect
                ticket = self._flow_admit("POST")
                code, obj = self._post_locked()
            if ticket is None:
                # 429 with no Retry-After: the shed contract broken
                self._json(429, {"error": "TooManyRequests"})
""")

GOOD_SHED = textwrap.dedent("""
    class Handler:
        def do_POST(self):
            ticket = self._flow_admit("POST")
            if ticket is None:
                return  # 429 + Retry-After already sent by _flow_admit
            try:
                with server._write_lock:
                    code, obj = self._post_locked()
            finally:
                server.flowcontrol.release(ticket)
            self._json(code, obj)

        def _flow_admit(self, method):
            ticket = server.flowcontrol.admit("workload", "ns")
            if ticket is None:
                self._json(429, {"error": "TooManyRequests"},
                           retry_after=1)
            return ticket
""")


class TestShedDisciplineFixtures:
    def test_flags_shed_violations(self):
        fs = check_source(checker_by_id("shed-discipline"), BAD_SHED)
        assert _rules(fs) == ["429-without-retry-after",
                              "shed-under-write-lock"]

    def test_passes_disciplined_shed_path(self):
        assert check_source(checker_by_id("shed-discipline"),
                            GOOD_SHED) == []

    def test_flowcontrol_admit_under_lock_flagged(self):
        bad = textwrap.dedent("""
            class Handler:
                def do_PUT(self):
                    with server._write_lock:
                        t = server.flowcontrol.admit("workload", "ns")
        """)
        fs = check_source(checker_by_id("shed-discipline"), bad)
        assert _rules(fs) == ["shed-under-write-lock"]

    def test_unrelated_admit_not_flagged(self):
        good = textwrap.dedent("""
            class Handler:
                def do_PUT(self):
                    with server._write_lock:
                        self.gatekeeper.admit(pod)  # not flow control
        """)
        assert check_source(checker_by_id("shed-discipline"), good) == []

    def test_retry_after_literal_outside_backoff_flagged(self):
        """A client module growing its own Retry-After parsing beside the
        shared backoff stack is the rot this rule exists for."""
        bad = textwrap.dedent("""
            def my_retry_loop(call):
                try:
                    return call()
                except Exception as e:
                    wait = float(e.headers.get("Retry-After", 1))
                    time.sleep(wait)
        """)
        fs = check_source(checker_by_id("shed-discipline"), bad,
                          path="shard/member.py")
        assert _rules(fs) == ["retry-after-parse-outside-backoff"]

    def test_retry_after_literal_in_seams_exempt(self):
        src = 'HEADER = "Retry-After"\n'
        for seam in ("core/backoff.py", "core/apiserver.py",
                     "core/flowcontrol.py"):
            assert check_source(checker_by_id("shed-discipline"), src,
                                path=seam) == []

    def test_scope(self):
        c = checker_by_id("shed-discipline")
        assert c.applies_to("core/apiserver.py")
        assert c.applies_to("shard/member.py")


class TestShardingDisciplineFixtures:
    """ISSUE 15 mesh-first plane: a bare jax.jit inside the sharded-state
    seam hands back GSPMD-chosen placements and silently retraces the
    session kernel on the next dispatch."""

    def test_bare_jit_with_sharded_state_param_flagged(self):
        bad = textwrap.dedent("""
            import jax

            def sharded_scatter(sharded_state, idx, rows):
                fn = jax.jit(scatter_impl)
                return fn(sharded_state, idx, rows)
        """)
        fs = check_source(checker_by_id("sharding-discipline"), bad)
        assert _rules(fs) == ["bare-jit-on-sharded-state"]

    def test_bare_jit_near_sharded_state_callsite_flagged(self):
        bad = textwrap.dedent("""
            import jax

            def apply_patch(self, updates, state):
                patch = jax.jit(patch_impl)
                new = self.mirror.patch_rows(updates, sharded_state=state)
                return patch(new)
        """)
        fs = check_source(checker_by_id("sharding-discipline"), bad)
        assert _rules(fs) == ["bare-jit-on-sharded-state"]

    def test_pinned_jit_passes(self):
        good = textwrap.dedent("""
            import jax

            def sharded_scatter(out_shardings, sharded_state, idx, rows):
                fn = jax.jit(scatter_impl, out_shardings=out_shardings)
                return fn(sharded_state, idx, rows)
        """)
        assert check_source(checker_by_id("sharding-discipline"),
                            good) == []

    def test_jit_wrapping_shard_map_exempt(self):
        """shard_map's in/out_specs ARE the placement pin."""
        good = textwrap.dedent("""
            import jax
            from jax.experimental.shard_map import shard_map

            def build(mesh, in_specs, out_specs, out_shardings):
                return jax.jit(shard_map(body, mesh=mesh,
                                         in_specs=in_specs,
                                         out_specs=out_specs))
        """)
        assert check_source(checker_by_id("sharding-discipline"),
                            good) == []

    def test_bare_jit_outside_seam_not_flagged(self):
        good = textwrap.dedent("""
            import jax

            def plain_helper(x):
                return jax.jit(lambda a: a + 1)(x)
        """)
        assert check_source(checker_by_id("sharding-discipline"),
                            good) == []

    def test_scope(self):
        c = checker_by_id("sharding-discipline")
        assert c.applies_to("ops/device_state.py")
        assert c.applies_to("parallel/mesh.py")
        assert c.applies_to("models/tpu_scheduler.py")
        assert not c.applies_to("core/apiserver.py")

    def test_shard_map_bodies_join_jit_purity_scope(self):
        """A shard_map-wrapped function is jit-reachable: impure host
        effects inside it are flagged by jit-purity (the ISSUE's 'bodies
        join the jit-purity scan scope')."""
        bad = textwrap.dedent("""
            import time
            from jax.experimental.shard_map import shard_map

            def body(x):
                time.sleep(1)
                return x

            def build(mesh, specs):
                return shard_map(body, mesh=mesh, in_specs=specs,
                                 out_specs=specs)
        """)
        fs = check_source(checker_by_id("jit-purity"), bad)
        assert any("time" in f.message or "impure" in f.message
                   for f in fs), fs


# ---------------------------------------------------------------------------
# the tree gate + allowlist policy
# ---------------------------------------------------------------------------


def test_tree_runs_clean():
    """The analyzer is a floor: the real package has zero findings (every
    violation the checkers surfaced during PR 7 was fixed, not
    allowlisted) and zero stale allowlist entries."""
    report = analyze()
    assert report.files_scanned > 50
    assert not report.findings, "\n".join(str(f) for f in report.findings)
    assert not report.unused_allows, report.unused_allows


def test_every_checker_registered_and_described():
    checkers = all_checkers()
    ids = sorted(c.id for c in checkers)
    assert ids == ["deschedule-discipline", "eviction-discipline",
                   "hint-freshness", "index-dtype",
                   "jit-purity", "lock-discipline", "metrics-discipline",
                   "reconcile-discipline", "sharding-discipline",
                   "shed-discipline", "span-discipline",
                   "supervision-discipline", "thread-hygiene",
                   "wire-discipline"]
    assert all(c.description for c in checkers)


def test_allowlist_reasons_are_mandatory():
    validate_allowlist(ALLOWLIST)  # current entries all carry reasons
    with pytest.raises(ValueError, match="no reason"):
        validate_allowlist([Allow("index-dtype", "ops/kernel.py", 1, "  ")])


def test_allowlist_suppresses_and_goes_stale():
    import pathlib
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        (root / "mod.py").write_text(
            "import jax.numpy as jnp\nix = jnp.arange(4)\n")
        hit = Allow("index-dtype", "mod.py", 2, "fixture: deliberate")
        report = analyze(root=root, allowlist=[hit])
        assert not report.findings and len(report.suppressed) == 1
        stale = Allow("index-dtype", "mod.py", 99, "fixture: wrong line")
        report = analyze(root=root, allowlist=[stale])
        assert len(report.findings) == 1 and report.unused_allows == [stale]


# ---------------------------------------------------------------------------
# fixture corpus: wire-discipline (PR 13)
# ---------------------------------------------------------------------------


class TestWireDiscipline:
    BAD_FANOUT = (
        "import json\n"
        "class S:\n"
        "    def _broadcast(self, event):\n"
        "        data = (json.dumps(event) + '\\n').encode()\n"
        "        self.fan(data)\n"
        "    def _tail(self, line):\n"
        "        return json.loads(line)\n")

    def test_json_on_hot_surface_flagged(self):
        fs = check_source(checker_by_id("wire-discipline"),
                          self.BAD_FANOUT, path="core/apiserver.py")
        assert {(f.rule, f.line) for f in fs} == {
            ("json-on-wire-surface", 4), ("json-on-wire-surface", 7)}

    def test_aliased_imports_resolved(self):
        aliased = (
            "import json as _j\n"
            "from json import loads as _loads\n"
            "def ship(rec, line):\n"
            "    return _j.dumps(rec), _loads(line)\n")
        fs = check_source(checker_by_id("wire-discipline"),
                          aliased, path="core/wal.py")
        assert len(fs) == 2 and all(
            f.rule == "json-on-wire-surface" for f in fs)

    def test_routing_through_the_seam_is_clean(self):
        good = (
            "from . import wire\n"
            "class S:\n"
            "    def _broadcast(self, event):\n"
            "        self.fan(wire.WireItem(event))\n"
            "    def _meta(self, raw):\n"
            "        return wire.jloads(raw)\n"
            "    def _reply(self, obj, codec):\n"
            "        return wire.encode(obj, codec)\n")
        assert check_source(checker_by_id("wire-discipline"),
                            good, path="core/watchcache.py") == []

    def test_non_hot_modules_and_the_seam_are_out_of_scope(self):
        src = "import json\nx = json.dumps({'a': 1})\n"
        # the codec seam itself IS the json call site
        assert check_source(checker_by_id("wire-discipline"),
                            src, path="core/wire.py") == []
        # harness/bench/debug modules keep plain json freely
        assert check_source(checker_by_id("wire-discipline"),
                            src, path="shard/harness.py") == []

    def test_tree_is_clean(self):
        checker = checker_by_id("wire-discipline")
        report = analyze(checkers=[checker], allowlist=[])
        assert report.findings == [], [str(f) for f in report.findings]


# ---------------------------------------------------------------------------
# fixture corpus: delta-base-under-cache-lock (PR 18)
# ---------------------------------------------------------------------------


class TestDeltaBaseUnderCacheLock:
    def test_unlocked_base_read_in_mint_flagged(self):
        bad = (
            "class WatchCache:\n"
            "    def mint_delta(self, event):\n"
            "        base = self._objects.get(event['key'])\n"
            "        with self._lock:\n"
            "            rv = self._obj_rv.get(event['key'])\n"
            "        return base, rv\n")
        fs = check_source(checker_by_id("wire-discipline"),
                          bad, path="core/watchcache.py")
        assert {(f.rule, f.line) for f in fs} == {
            ("delta-base-under-cache-lock", 3)}

    def test_unlocked_rv_read_in_materialize_flagged(self):
        bad = (
            "class WatchCache:\n"
            "    def materialize_delta(self, rec):\n"
            "        have = self._obj_rv.get(rec['key'])\n"
            "        return have\n")
        fs = check_source(checker_by_id("wire-discipline"),
                          bad, path="core/watchcache.py")
        assert [f.rule for f in fs] == ["delta-base-under-cache-lock"]

    def test_locked_reads_are_clean(self):
        good = (
            "class WatchCache:\n"
            "    def mint_delta(self, event):\n"
            "        with self._lock:\n"
            "            base = self._objects.get(event['key'])\n"
            "            rv = self._obj_rv.get(event['key'])\n"
            "        return base, rv\n"
            "    def materialize_delta(self, rec):\n"
            "        with self._lock:\n"
            "            return dict(self._objects.get(rec['key']) or {})\n")
        assert check_source(checker_by_id("wire-discipline"),
                            good, path="core/watchcache.py") == []

    def test_session_state_in_fanout_path_flagged(self):
        bad = (
            "from . import wire\n"
            "class S:\n"
            "    def _broadcast(self, event):\n"
            "        enc = wire.SessionEncoder()\n"
            "        self.fan(enc.encode(event))\n"
            "    def _route_to(self, st, item):\n"
            "        st.q.put(item.session_bytes(st.enc))\n")
        fs = check_source(checker_by_id("wire-discipline"),
                          bad, path="core/apiserver.py")
        assert {(f.rule, f.line) for f in fs} == {
            ("delta-base-under-cache-lock", 4),
            ("delta-base-under-cache-lock", 7)}

    def test_session_state_on_consumer_thread_is_clean(self):
        good = (
            "from . import wire\n"
            "class Handler:\n"
            "    def _stream(self, kind):\n"
            "        enc = wire.SessionEncoder()\n"
            "        while True:\n"
            "            item = self.q.get()\n"
            "            self.wfile.write(item.session_bytes(enc))\n")
        assert check_source(checker_by_id("wire-discipline"),
                            good, path="core/apiserver.py") == []

    def test_non_delta_functions_out_of_scope(self):
        # snapshot reads elsewhere in the cache (own-lock discipline is
        # the module's business) don't trip the delta rule
        src = (
            "class WatchCache:\n"
            "    def read_summary(self):\n"
            "        return len(self._objects)\n")
        assert check_source(checker_by_id("wire-discipline"),
                            src, path="core/watchcache.py") == []


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis", *args],
        capture_output=True, text=True, timeout=120)


def test_cli_exits_zero_on_the_tree():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_exits_nonzero_on_seeded_violations(tmp_path):
    """Acceptance: a seeded bare `jnp.arange` in ops/ and a WAL append
    outside the lock region must fail the scan, with --json detail."""
    ops = tmp_path / "ops"
    ops.mkdir()
    (ops / "bad_kernel.py").write_text(
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.arange(n)\n")
    core = tmp_path / "core"
    core.mkdir()
    (core / "apiserver.py").write_text(
        "class S:\n"
        "    def _broadcast(self, event):\n"
        "        self.persistence.append(event)\n")
    proc = _run_cli("--root", str(tmp_path), "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert not report["clean"]
    rules = {(f["checker"], f["rule"]) for f in report["findings"]}
    assert ("index-dtype", "arange-dtype") in rules
    assert ("lock-discipline", "wal-under-broadcast-lock") in rules


def test_cli_single_checker_and_listing():
    proc = _run_cli("--list-checkers")
    assert proc.returncode == 0
    assert "lock-discipline" in proc.stdout
    proc = _run_cli("--checker", "thread-hygiene")
    assert proc.returncode == 0, proc.stdout + proc.stderr


class TestEvictionDisciplineFixtures:
    """controllers/ pod delete/evict sites must sit on a call-graph slice
    holding BOTH the rate-limiter grant and the idempotent intent record
    (ISSUE 16: a naked eviction is unthrottled under zone disruption and
    replayable after a controller restart)."""

    def test_flags_naked_delete(self):
        bad = textwrap.dedent("""
            class Reaper:
                def drain(self, node):
                    for pod in self.cs.pods():
                        if pod.node_name == node:
                            self.cs.delete_pod(pod)
        """)
        fs = check_source(checker_by_id("eviction-discipline"), bad)
        assert _rules(fs) == ["eviction-outside-funnel"]
        assert len(fs) == 1

    def test_flags_limiter_without_intent(self):
        """A throttle with no ledger rate-limits the double-evictions —
        it does not prevent them. Still a finding."""
        bad = textwrap.dedent("""
            class Reaper:
                def drain(self, zone, pod):
                    if self._buckets[zone].try_take():
                        self.cs.evict_pod(pod.uid, pod.node_name, "x")
        """)
        fs = check_source(checker_by_id("eviction-discipline"), bad)
        assert _rules(fs) == ["eviction-outside-funnel"]

    def test_flags_intent_without_limiter(self):
        bad = textwrap.dedent("""
            class Reaper:
                def drain(self, pod):
                    intent = intent_for(pod.uid, pod.node_name)
                    self.cs.evict_pod(pod.uid, pod.node_name, intent)
        """)
        fs = check_source(checker_by_id("eviction-discipline"), bad)
        assert _rules(fs) == ["eviction-outside-funnel"]

    def test_passes_full_funnel_in_one_def(self):
        good = textwrap.dedent("""
            class Evictor:
                def drain(self, zone, pod):
                    if not self._buckets[zone].try_take():
                        return
                    intent = intent_for(pod.uid, pod.node_name)
                    self.cs.evict_pod(pod.uid, pod.node_name, intent)
        """)
        assert check_source(checker_by_id("eviction-discipline"), good) == []

    def test_passes_run_once_shape(self):
        """The real evictor's shape: the token is taken one frame above
        the intent stamp — the caller's slice covers the call site."""
        good = textwrap.dedent("""
            class Evictor:
                def run_once(self):
                    for zone, q in self._queues.items():
                        while q and self._buckets[zone].try_take():
                            self._evict_one(q.popleft())
                def _evict_one(self, item):
                    intent = intent_for(item.uid, item.node)
                    self.cs.evict_pod(item.uid, item.node, intent)
        """)
        assert check_source(checker_by_id("eviction-discipline"), good) == []

    def test_scope_is_controllers_only(self):
        ck = checker_by_id("eviction-discipline")
        assert ck.applies_to("kubernetes_tpu/controllers/evictor.py")
        assert ck.applies_to("controllers/node_lifecycle.py")
        assert not ck.applies_to("kubernetes_tpu/core/scheduler.py")
        assert not ck.applies_to("tests/test_node_lifecycle.py")

    def test_real_evictor_module_is_clean(self):
        import kubernetes_tpu.controllers.evictor as ev
        import inspect
        src = inspect.getsource(ev)
        assert check_source(checker_by_id("eviction-discipline"), src,
                            "kubernetes_tpu/controllers/evictor.py") == []

    def test_lock_discipline_scope_covers_controllers(self):
        """Satellite: the lock-discipline scan now walks controllers/ too —
        a sleep under a held lock in a controller module must flag."""
        ck = checker_by_id("lock-discipline")
        assert ck.applies_to("kubernetes_tpu/controllers/node_lifecycle.py")


class TestDescheduleDisciplineFixtures:
    """Descheduler modules under controllers/ may only emit evictions on
    a call-graph slice holding BOTH the scored-improvement gate and the
    deterministic intent record (ISSUE 20: an ungated move is churn —
    ping-pong between near-balanced nodes — and an unintended one is
    unreplayable across a standby takeover)."""

    def test_flags_ungated_unintended_move(self):
        bad = textwrap.dedent("""
            class Descheduler:
                def rebalance(self, plan):
                    for pod, node in plan:
                        self.evictor.enqueue("z", node, pod.uid)
        """)
        fs = check_source(checker_by_id("deschedule-discipline"), bad)
        assert _rules(fs) == ["move-without-scored-gate"]
        assert len(fs) == 1

    def test_flags_gate_without_intent(self):
        """Scored but anonymous: the takeover's re-derived wave cannot
        replay into the ledger. Still a finding."""
        bad = textwrap.dedent("""
            class Descheduler:
                def rebalance(self, moves, floor):
                    for mv in moves:
                        if clears_hysteresis(mv.improvement, floor):
                            self.evictor.enqueue("z", mv.node, mv.uid)
        """)
        fs = check_source(checker_by_id("deschedule-discipline"), bad)
        assert _rules(fs) == ["move-without-scored-gate"]

    def test_flags_intent_without_gate(self):
        bad = textwrap.dedent("""
            class Descheduler:
                def rebalance(self, moves):
                    for mv in moves:
                        intent = intent_for(mv.uid, mv.node)
                        self.cs.evict_pod(mv.uid, mv.node, intent)
        """)
        fs = check_source(checker_by_id("deschedule-discipline"), bad)
        assert _rules(fs) == ["move-without-scored-gate"]

    def test_passes_reconcile_emit_shape(self):
        """The real controller's shape: the gate runs in reconcile_once,
        the intent is minted one frame below in _emit — the caller's
        closure holds both sinks plus the emit site."""
        good = textwrap.dedent("""
            class Descheduler:
                def reconcile_once(self, cands, floor):
                    for c in cands:
                        if clears_hysteresis(c.improvement, floor):
                            self._emit(c)
                def _emit(self, c):
                    intent = intent_for(c.uid, c.node)
                    self.planned[c.uid] = intent
                    self.evictor.enqueue(c.zone, c.node, c.uid)
        """)
        assert check_source(checker_by_id("deschedule-discipline"),
                            good) == []

    def test_scope_is_descheduler_modules_only(self):
        """Composes with eviction-discipline: that one covers ALL of
        controllers/; this one only bites descheduler modules (the
        node-lifecycle evictor legitimately moves pods ungated — its
        seats are ILLEGAL, there is no score to clear)."""
        ck = checker_by_id("deschedule-discipline")
        assert ck.applies_to("kubernetes_tpu/controllers/descheduler.py")
        assert ck.applies_to("controllers/descheduler.py")
        assert not ck.applies_to(
            "kubernetes_tpu/controllers/node_lifecycle.py")
        assert not ck.applies_to("kubernetes_tpu/ops/whatif.py")
        assert not ck.applies_to("tests/test_descheduler.py")

    def test_real_descheduler_module_is_clean(self):
        import kubernetes_tpu.controllers.descheduler as ds
        import inspect
        src = inspect.getsource(ds)
        assert check_source(
            checker_by_id("deschedule-discipline"), src,
            "kubernetes_tpu/controllers/descheduler.py") == []


class TestReconcileDisciplineFixtures:
    """controllers/ pod create sites must sit on a call-graph slice
    holding BOTH a deterministic-name source and a create-409-is-success
    handler (ISSUE 17: HA reconcilers racing a lease — or one reconciler
    across a kill9 — must collide benignly, never duplicate pods)."""

    def test_flags_random_named_create(self):
        bad = textwrap.dedent("""
            import uuid
            class Reconciler:
                def heal(self, rs):
                    for _ in range(rs.missing):
                        self.cs.create_pod(self.pod(uuid.uuid4().hex))
        """)
        fs = check_source(checker_by_id("reconcile-discipline"), bad)
        assert _rules(fs) == ["create-outside-seam"]
        assert len(fs) == 1

    def test_flags_deterministic_name_without_409_seam(self):
        """Deterministic names alone still crash the CAS loser: the
        second actor's create raises 409 and the reconciler error-loops.
        Still a finding."""
        bad = textwrap.dedent("""
            class Reconciler:
                def heal(self, rs):
                    for i in range(rs.replicas):
                        name = replica_name(rs.name, rs.revision, i)
                        self.cs.create_pod(self.pod(name))
        """)
        fs = check_source(checker_by_id("reconcile-discipline"), bad)
        assert _rules(fs) == ["create-outside-seam"]

    def test_flags_409_seam_without_deterministic_name(self):
        """409-tolerance over random names never fires — the duplicates
        don't collide, they coexist. Still a finding."""
        bad = textwrap.dedent("""
            import uuid
            class Reconciler:
                def heal(self, rs):
                    try:
                        self.cs.create_pod(self.pod(uuid.uuid4().hex))
                    except HTTPError as e:
                        if e.code != 409:
                            raise
        """)
        fs = check_source(checker_by_id("reconcile-discipline"), bad)
        assert _rules(fs) == ["create-outside-seam"]

    def test_passes_full_seam_in_one_def(self):
        good = textwrap.dedent("""
            class Reconciler:
                def heal(self, rs, i):
                    name = replica_name(rs.name, rs.revision, i)
                    try:
                        self.cs.create_pod(self.pod(name))
                    except HTTPError as e:
                        if e.code != 409:
                            raise
        """)
        assert check_source(
            checker_by_id("reconcile-discipline"), good) == []

    def test_passes_mint_seam_shape(self):
        """The real controllers' shape: the name is derived one frame
        above the create seam — the caller's slice covers the site."""
        good = textwrap.dedent("""
            def _create_pod(cs, pod):
                try:
                    cs.create_pod(pod)
                    return True
                except HTTPError as e:
                    if e.code == 409:
                        return False
                    raise
            class Reconciler:
                def heal(self, rs):
                    for i in range(rs.replicas):
                        name = replica_name(rs.name, rs.revision, i)
                        _create_pod(self.cs, self.pod(name))
        """)
        assert check_source(
            checker_by_id("reconcile-discipline"), good) == []

    def test_scope_is_controllers_only(self):
        ck = checker_by_id("reconcile-discipline")
        assert ck.applies_to("kubernetes_tpu/controllers/workload.py")
        assert ck.applies_to("controllers/autoscaler.py")
        assert not ck.applies_to("kubernetes_tpu/core/scheduler.py")
        assert not ck.applies_to("tests/test_node_lifecycle.py")

    def test_real_workload_module_is_clean(self):
        import inspect

        import kubernetes_tpu.controllers.workload as wk
        src = inspect.getsource(wk)
        assert check_source(checker_by_id("reconcile-discipline"), src,
                            "kubernetes_tpu/controllers/workload.py") == []


def test_cli_seeded_racy_create_exits_nonzero(tmp_path):
    """Acceptance (ISSUE 17): `reconcile-discipline` exits 1 on a seeded
    racy-create fixture under controllers/."""
    ctl = tmp_path / "controllers"
    ctl.mkdir()
    (ctl / "healer.py").write_text(
        "import uuid\n"
        "class Healer:\n"
        "    def heal(self, rs):\n"
        "        self.cs.create_pod(self.pod(uuid.uuid4().hex))\n")
    proc = _run_cli("--root", str(tmp_path), "--checker",
                    "reconcile-discipline", "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    rules = {(f["checker"], f["rule"]) for f in report["findings"]}
    assert ("reconcile-discipline", "create-outside-seam") in rules


def test_cli_seeded_naked_delete_exits_nonzero(tmp_path):
    """Acceptance (ISSUE 16): `eviction-discipline` exits 1 on a seeded
    naked-delete fixture under controllers/."""
    ctl = tmp_path / "controllers"
    ctl.mkdir()
    (ctl / "reaper.py").write_text(
        "class Reaper:\n"
        "    def drain(self, node):\n"
        "        for pod in self.cs.pods():\n"
        "            self.cs.delete_pod(pod)\n")
    proc = _run_cli("--root", str(tmp_path), "--checker",
                    "eviction-discipline", "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    rules = {(f["checker"], f["rule"]) for f in report["findings"]}
    assert ("eviction-discipline", "eviction-outside-funnel") in rules


def test_cli_seeded_ungated_move_exits_nonzero(tmp_path):
    """Acceptance (ISSUE 20): `deschedule-discipline` exits 1 on a seeded
    ungated-move fixture under controllers/."""
    ctl = tmp_path / "controllers"
    ctl.mkdir()
    (ctl / "descheduler.py").write_text(
        "class Descheduler:\n"
        "    def rebalance(self, plan):\n"
        "        for pod, node in plan:\n"
        "            self.evictor.enqueue('z', node, pod.uid)\n")
    proc = _run_cli("--root", str(tmp_path), "--checker",
                    "deschedule-discipline", "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    rules = {(f["checker"], f["rule"]) for f in report["findings"]}
    assert ("deschedule-discipline", "move-without-scored-gate") in rules


class TestSupervisionDisciplineFixtures:
    """fleet/ child spawn sites must sit on a call-graph slice holding
    BOTH a readiness barrier and drain_pipe wiring (ISSUE 19: a spawn
    without the barrier races the staged bring-up; without the drain, a
    chatty child wedges on a full 64KB pipe — the PR-8 stall class)."""

    def test_flags_naked_popen_both_rules(self):
        bad = textwrap.dedent("""
            import subprocess

            class Conductor:
                def launch(self, cmd):
                    return subprocess.Popen(cmd)
        """)
        fs = check_source(checker_by_id("supervision-discipline"), bad,
                          "kubernetes_tpu/fleet/conductor.py")
        rules = {f.rule for f in fs}
        assert rules == {"spawn-no-barrier", "spawn-no-drain"}

    def test_flags_spawn_ready_without_drain(self):
        """spawn_ready IS the readiness barrier (it blocks on the child's
        first ready line) — but the drain still has to be wired."""
        bad = textwrap.dedent("""
            from ..testing.faults import spawn_ready

            class Conductor:
                def launch(self, member):
                    member.proc = spawn_ready(member.cmd, member.pattern)
        """)
        fs = check_source(checker_by_id("supervision-discipline"), bad,
                          "kubernetes_tpu/fleet/conductor.py")
        assert {f.rule for f in fs} == {"spawn-no-drain"}

    def test_passes_full_discipline_in_one_def(self):
        good = textwrap.dedent("""
            from ..testing.faults import drain_pipe, spawn_ready

            class Conductor:
                def launch(self, member):
                    member.proc = spawn_ready(member.cmd, member.pattern)
                    member.tail = drain_pipe(member.proc)
        """)
        assert check_source(checker_by_id("supervision-discipline"), good,
                            "kubernetes_tpu/fleet/conductor.py") == []

    def test_passes_barrier_one_frame_above_the_spawn(self):
        """The start → _start_shards → _spawn shape: a raw Popen in a
        helper is covered when a caller's slice holds the lease barrier
        and the drain wiring."""
        good = textwrap.dedent("""
            import subprocess

            class Conductor:
                def _spawn(self, cmd):
                    proc = subprocess.Popen(cmd)
                    self._tails.append(drain_pipe(proc))
                    return proc

                def start_shards(self):
                    for cmd in self.cmds:
                        self._spawn(cmd)
                    self._wait_shards_leased()

                def _wait_shards_leased(self):
                    pass
        """)
        assert check_source(checker_by_id("supervision-discipline"), good,
                            "kubernetes_tpu/fleet/conductor.py") == []

    def test_scope_is_fleet_only(self):
        ck = checker_by_id("supervision-discipline")
        assert ck.applies_to("kubernetes_tpu/fleet/conductor.py")
        assert ck.applies_to("fleet/__main__.py")
        assert not ck.applies_to("kubernetes_tpu/shard/harness.py")
        assert not ck.applies_to("kubernetes_tpu/testing/faults.py")
        assert not ck.applies_to("tests/test_fleet.py")

    def test_real_conductor_module_is_clean(self):
        import inspect

        import kubernetes_tpu.fleet.conductor as cond
        src = inspect.getsource(cond)
        assert check_source(checker_by_id("supervision-discipline"), src,
                            "kubernetes_tpu/fleet/conductor.py") == []

    def test_lock_discipline_scope_covers_fleet(self):
        """Satellite: the lock-discipline scan walks fleet/ too — a sleep
        under a held lock in the conductor must flag."""
        ck = checker_by_id("lock-discipline")
        assert ck.applies_to("kubernetes_tpu/fleet/conductor.py")
