"""Device-path gang scheduling: pod groups scheduled by the DEFAULT
algorithm ride device sessions (whole groups per dispatch, group-granular
commit barrier), with assignments identical to the host group cycle
(schedule_one_podgroup.go:556 member-wise placement semantics)."""

import pytest

from kubernetes_tpu.api.types import PodGroup
from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.models import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _cluster(cls, n_nodes=40, **kw):
    cs = FakeClientset()
    if cls is Scheduler:
        kw.setdefault("deterministic_ties", True)
    sched = cls(clientset=cs, **kw)
    for i in range(n_nodes):
        cs.create_node(
            make_node().name(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
            .zone(f"z{i % 4}").obj())
    return cs, sched


def _gangs(cs, n_groups, size, cpu="500m"):
    proto = make_pod().name("proto").req({"cpu": cpu, "memory": "128Mi"}).obj()
    pods = []
    for g in range(n_groups):
        cs.create_pod_group(PodGroup(name=f"g{g}", min_count=size))
        for j in range(size):
            p = proto.clone_from_template(f"pod-{g}-{j}")
            p.pod_group = f"g{g}"
            cs.create_pod(p)
            pods.append(p)
    return pods


def test_gang_device_assignments_match_host_oracle():
    cs_h, host = _cluster(Scheduler)
    ph = _gangs(cs_h, 12, 4)
    host.run_until_idle()
    cs_d, dev = _cluster(TPUScheduler)
    pd = _gangs(cs_d, 12, 4)
    dev.run_until_idle()
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd}
    assert hb == db
    assert dev.device_scheduled == 48
    assert dev.host_path_pods == 0


def test_gang_device_interleaved_with_plain_pods():
    cs_h, host = _cluster(Scheduler)
    ph = _gangs(cs_h, 6, 3)
    proto = make_pod().name("pp").req({"cpu": "250m"}).obj()
    plain_h = [proto.clone_from_template(f"plain-{i}") for i in range(20)]
    for p in plain_h:
        cs_h.create_pod(p)
    host.run_until_idle()

    cs_d, dev = _cluster(TPUScheduler)
    pd = _gangs(cs_d, 6, 3)
    proto_d = make_pod().name("pp").req({"cpu": "250m"}).obj()
    plain_d = [proto_d.clone_from_template(f"plain-{i}") for i in range(20)]
    for p in plain_d:
        cs_d.create_pod(p)
    dev.run_until_idle()

    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph + plain_h}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd + plain_d}
    assert hb == db
    assert dev.scheduled == 38


def test_gang_device_infeasible_group_parks_and_session_recovers():
    cs, dev = _cluster(TPUScheduler, n_nodes=4)
    # Feasible group, then an infeasible one (no node has 16 cpu), then
    # another feasible one — the session must survive with correct commits.
    ok1 = _gangs(cs, 1, 2, cpu="1")
    cs.create_pod_group(PodGroup(name="nofit", min_count=2))
    nf_proto = make_pod().name("nf").req({"cpu": "16"}).obj()
    nfs = []
    for j in range(2):
        p = nf_proto.clone_from_template(f"nf-{j}")
        p.pod_group = "nofit"
        cs.create_pod(p)
        nfs.append(p)
    dev.run_until_idle()
    ok2_proto = make_pod().name("ok2").req({"cpu": "1"}).obj()
    cs.create_pod_group(PodGroup(name="late", min_count=2))
    lates = []
    for j in range(2):
        p = ok2_proto.clone_from_template(f"late-{j}")
        p.pod_group = "late"
        cs.create_pod(p)
        lates.append(p)
    dev.run_until_idle()
    assert all(cs.bindings.get(p.uid) for p in ok1)
    assert all(cs.bindings.get(p.uid) is None for p in nfs)
    assert all(cs.bindings.get(p.uid) for p in lates)


def test_gang_member_anti_affinity_takes_host_path():
    """Members with pod anti-affinity are outside the gang device ring only
    when unsupported; hostname anti-affinity IS kernel-supported, so the
    group still rides the device and never co-locates."""
    cs, dev = _cluster(TPUScheduler, n_nodes=6)
    cs.create_pod_group(PodGroup(name="anti", min_count=3))
    proto = (make_pod().name("a").labels({"app": "x"})
             .pod_affinity("kubernetes.io/hostname", {"app": "x"}, anti=True)
             .req({"cpu": "100m"}).obj())
    pods = []
    for j in range(3):
        p = proto.clone_from_template(f"anti-{j}")
        p.pod_group = "anti"
        cs.create_pod(p)
        pods.append(p)
    dev.run_until_idle()
    nodes = [cs.bindings.get(p.uid) for p in pods]
    assert None not in nodes
    assert len(set(nodes)) == 3


def test_placement_gang_device_matches_host_oracle():
    """Topology-constrained gangs: the stacked kernel placement evaluation
    (ops/kernel.py schedule_placements) produces assignments identical to
    the host placement-simulation loop, and actually engages (counter)."""
    from kubernetes_tpu.core.registry import gang_placement_profiles

    ZONE = "topology.kubernetes.io/zone"

    def run(cls):
        cs = FakeClientset()
        kw = dict(profile_factory=gang_placement_profiles)
        if cls is Scheduler:
            kw["deterministic_ties"] = True
        sched = cls(clientset=cs, **kw)
        for i in range(30):
            cs.create_node(
                make_node().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
                .zone(f"z{i % 3}").obj())
        proto = make_pod().name("proto").req({"cpu": "500m"}).obj()
        pods = []
        for g in range(6):
            cs.create_pod_group(PodGroup(
                name=f"g{g}", min_count=3, topology_keys=(ZONE,)))
            for j in range(3):
                p = proto.clone_from_template(f"pod-{g}-{j}")
                p.pod_group = f"g{g}"
                cs.create_pod(p)
                pods.append(p)
        sched.run_until_idle()
        return cs, sched, pods

    cs_h, host, ph = run(Scheduler)
    cs_d, dev, pd = run(TPUScheduler)
    hb = {p.name: cs_h.bindings.get(p.uid) for p in ph}
    db = {p.name: cs_d.bindings.get(p.uid) for p in pd}
    assert hb == db
    assert dev.placement_device_evals == 6


class TestGangsWithClaims:
    """PVC-carrying gangs ride device sessions (round-4 VERDICT item 6):
    per-member claims dedup at the session seam, the counted CSI
    attach-limit constraint rides the kernel's aux lane, and commits match
    the host group cycle exactly."""

    def _populate(self, cs, n_nodes=8, n_groups=6, size=3, limit=4):
        from kubernetes_tpu.api.storage import (CSINode, PersistentVolume,
                                                PersistentVolumeClaim)
        from kubernetes_tpu.api.types import Volume
        for i in range(n_nodes):
            cs.create_node(
                make_node().name(f"n{i}")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": 110}).obj())
            cs.create_csi_node(CSINode(node_name=f"n{i}",
                                       driver_limits={"csi.x": limit}))
        pods = []
        for g in range(n_groups):
            cs.create_pod_group(PodGroup(name=f"g{g}", min_count=size))
            for j in range(size):
                pv = PersistentVolume.of(f"pv-{g}-{j}", "10Gi",
                                         storage_class="fast",
                                         csi_driver="csi.x")
                cs.create_pv(pv)
                cs.create_pvc(PersistentVolumeClaim.of(
                    f"c-{g}-{j}", "5Gi", storage_class="fast",
                    volume_name=pv.name))
                # Built individually (NOT clone_from_template: clones share
                # spec, and each member needs its own volume).
                p = make_pod().name(f"pod-{g}-{j}").req(
                    {"cpu": "500m", "memory": "128Mi"}).obj()
                p.pod_group = f"g{g}"
                p.volumes.append(Volume(name="data", pvc_name=f"c-{g}-{j}"))
                cs.create_pod(p)
                pods.append(p)
        return pods

    def test_pvc_gangs_device_match_host(self):
        results = {}
        for cls in (Scheduler, TPUScheduler):
            cs, sched = FakeClientset(), None
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            sched = cls(clientset=cs, **kw)
            pods = self._populate(cs)
            sched.run_until_idle()
            results[cls] = ({p.name: cs.bindings.get(p.uid) for p in pods},
                            sched)
        h, host = results[Scheduler]
        d, dev = results[TPUScheduler]
        assert h == d, {k: (h[k], d[k]) for k in h if h[k] != d[k]}
        assert all(h.values()), "all 18 members bound"
        total = len(h)
        assert dev.device_scheduled >= 0.8 * total, (
            f"only {dev.device_scheduled}/{total} device-scheduled "
            f"(host_path={dev.host_path_pods})")

    def test_attach_limit_exhaustion_matches_host(self):
        """2 nodes x limit 2: only 4 of 6 claims can attach; which members
        park must match the host oracle."""
        results = {}
        for cls in (Scheduler, TPUScheduler):
            cs = FakeClientset()
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            sched = cls(clientset=cs, **kw)
            pods = self._populate(cs, n_nodes=2, n_groups=3, size=2, limit=2)
            sched.run_until_idle()
            results[cls] = {p.name: cs.bindings.get(p.uid) for p in pods}
        assert results[Scheduler] == results[TPUScheduler]
        bound = sum(1 for v in results[Scheduler].values() if v)
        assert bound == 4, results[Scheduler]

    def test_shared_claim_within_gang_takes_host_path(self):
        """Two members sharing one claim would double-count on device: the
        group must fall back, and outcomes still match the host."""
        from kubernetes_tpu.api.storage import (CSINode, PersistentVolume,
                                                PersistentVolumeClaim)
        from kubernetes_tpu.api.types import Volume
        results = {}
        for cls in (Scheduler, TPUScheduler):
            cs = FakeClientset()
            kw = {"deterministic_ties": True} if cls is Scheduler else {}
            sched = cls(clientset=cs, **kw)
            for i in range(4):
                cs.create_node(make_node().name(f"n{i}")
                               .capacity({"cpu": "8", "pods": 110}).obj())
                cs.create_csi_node(CSINode(node_name=f"n{i}",
                                           driver_limits={"csi.x": 2}))
            pv = PersistentVolume.of("pv-s", "10Gi", storage_class="fast",
                                     csi_driver="csi.x")
            cs.create_pv(pv)
            cs.create_pvc(PersistentVolumeClaim.of(
                "shared", "5Gi", storage_class="fast", volume_name="pv-s"))
            cs.create_pod_group(PodGroup(name="g", min_count=2))
            pods = []
            for j in range(2):
                p = make_pod().name(f"m{j}").req({"cpu": "500m"}).obj()
                p.pod_group = "g"
                p.volumes.append(Volume(name="data", pvc_name="shared"))
                cs.create_pod(p)
                pods.append(p)
            sched.run_until_idle()
            results[cls] = {p.name: cs.bindings.get(p.uid) for p in pods}
        assert results[Scheduler] == results[TPUScheduler]
        assert all(results[Scheduler].values())
