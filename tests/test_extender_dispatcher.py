"""Extenders (extender.go), async API dispatcher (backend/api_dispatcher),
and QueueingHints (scheduling_queue.go:582)."""

import time

from kubernetes_tpu.core.api_dispatcher import (
    APICall,
    APIDispatcher,
    CALL_BINDING,
    CALL_STATUS_PATCH,
)
from kubernetes_tpu.core.config import SchedulerConfiguration
from kubernetes_tpu.core.extender import Extender
from kubernetes_tpu.core.queue import (
    EVENT_ASSIGNED_POD_DELETE,
    EVENT_NODE_ADD,
)
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _fake_transport(behavior):
    """behavior: dict verb -> callable(payload) -> dict (fake_extender.go)."""
    def call(verb, payload):
        return behavior[verb](payload)
    return call


class TestExtender:
    def _sched(self, ext):
        cfg = SchedulerConfiguration()
        cfg.extenders = [ext]
        s = Scheduler(config=cfg, deterministic_ties=True)
        for i in range(4):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        return s

    def test_extender_filter_narrows(self):
        ext = Extender(name="x", filter_verb="filter", transport=_fake_transport({
            "filter": lambda p: {"nodenames": ["n2"]}}))
        s = self._sched(ext)
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n2"]

    def test_extender_prioritize(self):
        ext = Extender(name="x", prioritize_verb="prioritize", weight=10,
                       transport=_fake_transport({
                           "prioritize": lambda p: {"hostPriorityList": [
                               {"host": "n3", "score": 10}]}}))
        s = self._sched(ext)
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert list(s.clientset.bindings.values()) == ["n3"]

    def test_extender_bind(self):
        bound = {}

        def do_bind(p):
            bound[p["podUID"]] = p["node"]
            return {}

        ext = Extender(name="x", bind_verb="bind",
                       transport=_fake_transport({"bind": do_bind}))
        s = self._sched(ext)
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        s.clientset.create_pod(pod)
        s.run_until_idle()
        assert bound.get(pod.uid)  # bind went through the extender

    def test_ignorable_extender_error(self):
        def boom(p):
            raise RuntimeError("down")
        ext = Extender(name="x", filter_verb="filter", ignorable=True,
                       transport=_fake_transport({"filter": boom}))
        s = self._sched(ext)
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 1  # ignorable: scheduling proceeds

    def test_managed_resources_gating(self):
        calls = []
        ext = Extender(name="x", filter_verb="filter",
                       managed_resources=("example.com/gpu",),
                       transport=_fake_transport({
                           "filter": lambda p: calls.append(1) or {"nodenames": []}}))
        s = self._sched(ext)
        s.clientset.create_pod(make_pod().name("cpu-only").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 1 and not calls  # not interested → not called


class TestAPIDispatcher:
    def test_inline_executes_immediately(self):
        d = APIDispatcher(mode="inline")
        hit = []
        d.add(APICall(CALL_BINDING, "u1", lambda: hit.append(1)))
        assert hit == [1] and d.executed == 1

    def test_thread_mode_merging(self):
        d = APIDispatcher(mode="thread")
        try:
            import threading
            gate = threading.Event()
            done = []
            # Block the worker with one slow call, then pile up mergeable calls.
            d.add(APICall(CALL_BINDING, "slow", lambda: gate.wait(2)))
            time.sleep(0.05)
            d.add(APICall(CALL_STATUS_PATCH, "p1", lambda: done.append("patch1")))
            d.add(APICall(CALL_STATUS_PATCH, "p1", lambda: done.append("patch2")))
            d.add(APICall(CALL_BINDING, "p1", lambda: done.append("bind")))
            gate.set()
            d.flush()
            # patch slot was replaced then superseded by the binding.
            assert done == ["bind"], done
            assert d.merged == 2
        finally:
            d.close()

    def test_scheduler_thread_dispatch(self):
        cfg = SchedulerConfiguration(async_dispatch_threads=True)
        s = Scheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        s.api_dispatcher.flush()
        assert len(s.clientset.bindings) == 1
        s.api_dispatcher.close()


class TestQueueingHints:
    def test_node_add_requeues_fit_failure(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("small").capacity({"cpu": "1", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("big").req({"cpu": "8"}).obj())
        s.run_until_idle()
        assert s.scheduled == 0
        s.clientset.create_node(
            make_node().name("big-node").capacity({"cpu": "16", "pods": 10}).obj())
        s.run_until_idle()
        assert s.scheduled == 1

    def test_irrelevant_event_does_not_requeue(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10})
            .label("disk", "hdd").obj())
        s.clientset.create_pod(
            make_pod().name("needs-ssd").req({"cpu": "1"})
            .node_selector({"disk": "ssd"}).obj())
        s.run_until_idle()
        assert s.scheduled == 0
        # An assigned-pod delete can't fix a NodeAffinity rejection.
        victim = make_pod().name("v").req({"cpu": "1"}).obj()
        s.clientset.create_pod(victim)
        s.run_until_idle()
        s.clientset.delete_pod(victim)
        active, backoff, unsched = s.queue.pending_counts()
        assert unsched == 1 and active == 0 and backoff == 0


class TestDispatcherBarrierAndErrors:
    def test_flush_waits_for_in_flight_call(self):
        """flush() is a true drain barrier: it waits for the worker to FINISH
        the popped call, not just for the queue to empty."""
        import threading
        import time as _t
        d = APIDispatcher(mode="thread")
        started = threading.Event()
        done = []

        def slow():
            started.set()
            _t.sleep(0.2)
            done.append(True)

        d.add(APICall("pod_binding", "u1", execute=slow))
        started.wait(1.0)
        d.flush()
        assert done, "flush returned while the call was still executing"
        d.close()

    def test_thread_mode_on_error_deferred_to_inbox(self):
        """Worker-thread failures do NOT run on_error on the worker; the
        scheduling loop drains them via drain_errors()."""
        import threading
        d = APIDispatcher(mode="thread")
        seen = []

        def boom():
            raise RuntimeError("api down")

        d.add(APICall("pod_binding", "u1", execute=boom,
                      on_error=lambda e: seen.append(threading.current_thread())))
        d.flush()
        assert not seen, "on_error ran on the worker thread"
        drained = d.drain_errors()
        assert len(drained) == 1
        call, exc = drained[0]
        call.on_error(exc)
        assert seen and seen[0] is threading.main_thread()
        d.close()


def test_extender_preempt_verb_narrows_candidates():
    """ProcessPreemption (extender.go:46-49): a preempt-capable extender
    restricts which nodes/victims preemption may use; the scheduler then
    nominates only an accepted node."""
    from kubernetes_tpu.core.clientset import FakeClientset

    calls = {}

    def transport(verb, payload):
        if verb == "preempt":
            calls["preempt"] = payload
            # accept only node n1, all its victims
            accepted = {n: v for n, v in payload["nodeNameToVictims"].items()
                        if n == "n1"}
            return {"nodeNameToVictims": accepted}
        return {}

    ext = Extender(name="pe", preempt_verb="preempt", transport=transport)
    cs = FakeClientset()
    sched = Scheduler(clientset=cs, deterministic_ties=True)
    sched.extenders.append(ext)
    for i in range(2):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "2", "pods": 10}).obj())
    victims = []
    for i in range(2):
        v = make_pod().name(f"victim-{i}").req({"cpu": "2"}).priority(0).obj()
        cs.create_pod(v)
        victims.append(v)
    sched.run_until_idle()
    assert all(cs.bindings.get(v.uid) for v in victims)
    high = make_pod().name("high").req({"cpu": "2"}).priority(100).obj()
    cs.create_pod(high)
    sched.run_until_idle()
    assert "preempt" in calls
    assert high.nominated_node_name == "n1"
