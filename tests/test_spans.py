"""End-to-end scheduling telemetry (core/spans.py; docs/OBSERVABILITY.md):
deterministic head sampling, ring-buffer wraparound, cross-process trace
context propagation over the real apiserver wire (bind POST → WAL → BOUND
event → foreign observer span), the crash-safe flight recorder (SIGUSR2 +
real two-OS-process artifacts), StepTrace slow-step span events, the
/debug/events read surface, and the trace analyzer CLI's golden output on
a recorded fixture trace."""

import io
import json
import logging
import os
import signal
import sys
import time

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler, spans
from kubernetes_tpu.core.spans import (FlightRecorder, SpanRecorder,
                                       format_ctx, parse_ctx, trace_id_for,
                                       write_jsonl)
from kubernetes_tpu.testing.wrappers import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def tracer():
    """Fresh sample-everything tracer installed as the process default;
    restored afterward so other tests keep the head-sampled default."""
    prev = spans.default_tracer()
    t = SpanRecorder(sample_n=1, proc="test")
    spans.set_default_tracer(t)
    yield t
    spans.set_default_tracer(prev)


def _node(name, cpu="8", pods=110):
    return (make_node().name(name)
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": pods}).obj())


def _pod(name, cpu="200m"):
    return make_pod().name(name).req({"cpu": cpu, "memory": "128Mi"}).obj()


# ---------------------------------------------------------------------------
# sampling + ring mechanics
# ---------------------------------------------------------------------------


class TestSampling:
    def test_sampling_is_deterministic_across_processes(self):
        """Two independent tracers (≈ two processes) must agree on every
        pod's trace id AND sampling verdict with no coordination — the
        property the whole cross-process merge stands on."""
        a = SpanRecorder(sample_n=16, proc="a")
        b = SpanRecorder(sample_n=16, proc="b")
        for i in range(500):
            uid = f"uid-{i}"
            ca, cb = a.context_for(uid), b.context_for(uid)
            assert ca.trace_id == cb.trace_id == trace_id_for(uid)
            assert ca.sampled == cb.sampled
        sampled = sum(a.context_for(f"uid-{i}").sampled for i in range(500))
        # 1-in-16 head sampling: statistically ~31 of 500
        assert 5 <= sampled <= 100

    def test_force_overrides_head_sampling(self):
        t = SpanRecorder(sample_n=1 << 30)  # nothing head-samples
        uid = "conflict-pod"
        assert not t.context_for(uid).sampled
        forced = t.context_for(uid, force=True)
        assert forced.sampled and forced.trace_id == trace_id_for(uid)
        # the base memo is NOT poisoned by the forced copy
        assert not t.context_for(uid).sampled

    def test_wire_context_roundtrip(self):
        ctx = SpanRecorder(sample_n=1).context_for("u1")
        wire = format_ctx(ctx)
        back = parse_ctx(wire)
        assert back.trace_id == ctx.trace_id and back.sampled
        assert parse_ctx("garbage") is None
        off = parse_ctx(f"{ctx.trace_id}-00")
        assert off is not None and not off.sampled

    def test_ring_buffer_wraparound(self):
        t = SpanRecorder(capacity=8, sample_n=1)
        for i in range(20):
            t.record(f"s{i}", t.context_for(f"u{i}"))
        rows = t.snapshot()
        assert len(rows) == 8
        assert [r["name"] for r in rows] == [f"s{i}" for i in range(12, 20)]
        assert t.recorded == 20  # accepted count survives eviction

    def test_disabled_tracer_records_nothing(self):
        t = SpanRecorder(sample_n=1, enabled=False)
        t.record("x", t.context_for("u"))
        with t.span("y", t.context_for("u")):
            pass
        assert t.snapshot() == []

    def test_scoped_span_records_error_attr(self):
        t = SpanRecorder(sample_n=1)
        with pytest.raises(ValueError):
            with t.span("stage", t.context_for("u")):
                raise ValueError("boom")
        (row,) = t.snapshot()
        assert row["attrs"]["error"] == "ValueError"


# ---------------------------------------------------------------------------
# in-process pipeline chain + e2e histogram
# ---------------------------------------------------------------------------


class TestSchedulerSpans:
    def test_host_path_chain_and_e2e_histogram(self, tracer):
        cs = FakeClientset()
        s = Scheduler(clientset=cs, deterministic_ties=True)
        for i in range(4):
            cs.create_node(_node(f"n{i}"))
        for i in range(6):
            cs.create_pod(_pod(f"p{i}"))
        s.run_until_idle()
        assert s.scheduled == 6
        names = {r["name"] for r in s.tracer.snapshot()}
        assert {"queue.admission", "queue.wait",
                "host.commit", "pod.e2e"} <= names
        # e2e histogram fed for EVERY bound pod (latency truth, unsampled
        # pods included) and exposed on /metrics
        assert s.metrics.e2e_scheduling_duration.count() == 6
        assert ("scheduler_e2e_scheduling_duration_seconds"
                in s.expose_metrics())

    def test_unsampled_pods_feed_histogram_but_not_ring(self):
        prev = spans.default_tracer()
        spans.set_default_tracer(SpanRecorder(sample_n=1 << 30, proc="off"))
        try:
            cs = FakeClientset()
            s = Scheduler(clientset=cs, deterministic_ties=True)
            cs.create_node(_node("n0"))
            cs.create_pod(_pod("p0"))
            s.run_until_idle()
            assert s.scheduled == 1
            assert s.metrics.e2e_scheduling_duration.count() == 1
            assert s.tracer.snapshot() == []
        finally:
            spans.set_default_tracer(prev)

    def test_bind_conflict_records_forced_span(self, tracer):
        from tests.test_shard_plane import _Conflict409, _ConflictOnce

        cs = FakeClientset()
        sched = Scheduler(clientset=_ConflictOnce(cs),
                          deterministic_ties=True)
        for i in range(4):
            cs.create_node(_node(f"n{i}"))
        cs.create_pod(_pod("racer"))
        sched.run_until_idle()
        rows = [r for r in sched.tracer.snapshot()
                if r["name"] == "bind.conflict"]
        assert len(rows) == 1
        assert rows[0]["attrs"]["reason"] == "already_bound"
        assert rows[0]["attrs"]["node"]
        assert rows[0]["trace"] == trace_id_for(
            next(iter(cs.pods.values())).uid)

    def test_device_path_records_stage_spans(self, tracer):
        from kubernetes_tpu.models import TPUScheduler

        cs = FakeClientset()
        s = TPUScheduler(clientset=cs)
        for i in range(8):
            cs.create_node(_node(f"n{i}", cpu="32"))
        proto = _pod("proto", cpu="100m")
        for i in range(32):
            cs.create_pod(proto.clone_from_template(f"p{i}"))
        s.run_until_idle()
        assert s.device_scheduled > 0
        names = {r["name"] for r in s.tracer.snapshot()}
        assert {"queue.wait", "plan.build", "device.dispatch",
                "device.wait", "host.commit", "pod.e2e"} <= names
        kinds = {r["attrs"].get("kind") for r in s.tracer.snapshot()
                 if r["name"] == "plan.build"}
        assert kinds & {"full", "delta", "resume"}
        # span ends also feed the extension-point histogram (p50/p99 truth)
        h = s.metrics.framework_extension_point_duration
        for point in ("DevicePlan", "DeviceWait", "HostCommit"):
            assert h.count(point, "Success", "") >= 1, point


# ---------------------------------------------------------------------------
# cross-process propagation over the real wire
# ---------------------------------------------------------------------------


class TestWirePropagation:
    def test_trace_id_survives_bind_wal_bound_observer(self, tracer, tmp_path):
        """bind POST → apiserver commit → WAL append → slim BOUND event →
        a SECOND watch client's bound.observe span, all under the pod's
        deterministic trace id; the WAL record preserves the context."""
        from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset

        api = APIServer(data_dir=str(tmp_path / "state"))
        api.tracer = tracer
        port = api.serve(0)
        binder = observer = None
        try:
            binder = HTTPClientset(f"http://127.0.0.1:{port}")
            observer = HTTPClientset(f"http://127.0.0.1:{port}")
            binder.create_node(_node("n0"))
            p = _pod("traced")
            binder.create_pod(p)
            binder.bind(p, "n0")
            # Wait for the BOUND event on BOTH watch streams: each records
            # its bound.observe before updating its bindings cache.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if all(c.bindings.get(p.uid) == "n0"
                       for c in (binder, observer)):
                    break
                time.sleep(0.02)
            assert observer.bindings.get(p.uid) == "n0"
            assert binder.bindings.get(p.uid) == "n0"
            tid = trace_id_for(p.uid)
            names = sorted(r["name"] for r in tracer.snapshot()
                           if r["trace"] == tid)
            # binder + observer both decode the BOUND event → 2 observes
            assert names == ["api.bind", "bind.post", "bound.fanout",
                             "bound.observe", "bound.observe", "wal.append"]
            # WAL records are binary wire frames now (core/wire.py):
            # interning splits a string's bytes across define/ref sites,
            # so decode the records instead of grepping raw text.
            from kubernetes_tpu.core import wire as _wire
            buf = (tmp_path / "state" / "wal.log").read_bytes()
            tctxs, pos = [], 0
            while True:
                got = _wire.scan(buf, pos)
                if got is None:
                    break
                rec, pos = got
                tctx = (rec.get("object") or {}).get("tctx")
                if tctx:
                    tctxs.append(tctx)
            assert format_ctx(tracer.context_for(p.uid)) in tctxs
        finally:
            for c in (binder, observer):
                if c is not None:
                    c.close()
            api.shutdown()

    def test_bulk_bind_items_carry_context(self, tracer):
        from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset

        api = APIServer()
        api.tracer = tracer
        port = api.serve(0)
        cs = None
        try:
            cs = HTTPClientset(f"http://127.0.0.1:{port}")
            cs.create_node(_node("n0", cpu="32"))
            pods = [_pod(f"b{i}", cpu="100m") for i in range(4)]
            for p in pods:
                cs.create_pod(p)
            assert cs.bind_many([(p, "n0") for p in pods]) == [None] * 4
            rows = tracer.snapshot()
            posts = [r for r in rows if r["name"] == "bind.post"]
            assert len(posts) == 4
            assert all(r["attrs"]["bulk"] == 4 for r in posts)
            binds = {r["trace"] for r in rows if r["name"] == "api.bind"}
            assert binds == {trace_id_for(p.uid) for p in pods}
        finally:
            if cs is not None:
                cs.close()
            api.shutdown()

    @pytest.mark.chaos
    def test_real_two_process_roundtrip_artifact(self, tracer, tmp_path):
        """REAL two-OS-process round trip: the apiserver runs as its own
        process (flight recorder installed into its data dir), the client
        binds over the socket, and the server's flight-recorder artifact
        holds the server-side half of the SAME trace id."""
        from kubernetes_tpu.core.apiserver import HTTPClientset
        from kubernetes_tpu.testing.faults import ApiServerProcess

        api = ApiServerProcess(str(tmp_path / "state"))
        cs = None
        try:
            cs = HTTPClientset(api.url)
            cs.create_node(_node("n0"))
            p = _pod("crosswire")
            cs.create_pod(p)
            cs.bind(p, "n0")
            # The BOUND event arrives asynchronously on the watch stream;
            # _dispatch records bound.observe BEFORE updating the bindings
            # cache on the same thread, so the cache is the ready signal.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if cs.bindings.get(p.uid) == "n0":
                    break
                time.sleep(0.02)
            tid = trace_id_for(p.uid)
            local = {r["name"] for r in tracer.snapshot()
                     if r["trace"] == tid}
            assert {"bind.post", "bound.observe"} <= local
        finally:
            if cs is not None:
                cs.close()
            api.stop()  # SIGTERM → graceful shutdown dump
        arts = [f for f in os.listdir(tmp_path / "state")
                if f.startswith("flightrec-") and f.endswith(".jsonl")]
        assert arts, "apiserver process left no flight-recorder artifact"
        rows = []
        for a in arts:
            with open(tmp_path / "state" / a) as f:
                rows.extend(json.loads(line) for line in f if line.strip())
        server_side = {r["name"] for r in rows
                       if r.get("kind") == "span" and r.get("trace") == tid}
        assert {"api.bind", "wal.append", "bound.fanout"} <= server_side
        assert any(r.get("kind") == "meta" and r.get("proc") == "apiserver"
                   for r in rows)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_dump_on_sigusr2_and_parses(self, tracer, tmp_path):
        cs = FakeClientset()
        s = Scheduler(clientset=cs, deterministic_ties=True)
        cs.create_node(_node("n0"))
        cs.create_pod(_pod("p0"))
        s.run_until_idle()
        fr = FlightRecorder(str(tmp_path), tracer=tracer,
                            recorder=s.recorder, scheduler=s).install(
            on_crash=False)
        try:
            signal.raise_signal(signal.SIGUSR2)
            path = tmp_path / f"flightrec-{os.getpid()}.jsonl"
            assert path.exists()
            rows = [json.loads(line) for line in path.read_text().splitlines()]
            kinds = {r["kind"] for r in rows}
            assert {"meta", "span", "event", "counters"} <= kinds
            meta = rows[0]
            assert meta["kind"] == "meta" and meta["reason"] == "sigusr2"
            counters = next(r for r in rows if r["kind"] == "counters")
            assert counters["scheduled"] == 1
            assert any(r["kind"] == "event" and r["reason"] == "Scheduled"
                       for r in rows)
        finally:
            fr.close()

    def test_rate_limited_request_dump_and_slow_step_trigger(
            self, tracer, tmp_path, caplog):
        from kubernetes_tpu.core.tracing import StepTrace

        fr = FlightRecorder(str(tmp_path), tracer=tracer).install(
            sigusr2=False, on_crash=False)
        try:
            tr = StepTrace("Scheduling", ctx=tracer.context_for("slowpod"),
                           pod="default/slowpod")
            tr.t0 -= 0.5
            tr._last = tr.t0
            tr.step("plan build")
            tr.step("fast tail")
            with caplog.at_level(logging.WARNING, logger="kubernetes_tpu"):
                tr.log_if_long()
            # offending step named explicitly (utiltrace stepThreshold)
            assert any("slow step(s) over" in r.getMessage()
                       and "plan build" in r.getMessage()
                       for r in caplog.records)
            # a span event per offending step, on the pod's trace
            slow = [r for r in tracer.snapshot()
                    if r["name"] == "trace.slow_step"]
            assert slow and slow[0]["attrs"]["step"] == "plan build"
            assert slow[0]["trace"] == trace_id_for("slowpod")
            # the breach dumped the flight recorder (then rate-limits)
            assert fr.dumps == 1
            assert fr.dump("again", rate_limited=True) is None
        finally:
            fr.close()

    def test_individual_slow_step_without_pod_ctx_uses_proc_ctx(self, tracer):
        from kubernetes_tpu.core.tracing import StepTrace

        tr = StepTrace("Scheduling", pod="default/anon")
        tr.t0 -= 0.3
        tr._last = tr.t0
        tr.step("everything")
        tr.log_if_long()
        slow = [r for r in tracer.snapshot() if r["name"] == "trace.slow_step"]
        assert slow and slow[0]["trace"] == tracer.proc_ctx().trace_id

    def test_autodump_timer_leaves_periodic_artifacts(self, tracer, tmp_path):
        fr = FlightRecorder(str(tmp_path), tracer=tracer).install(
            sigusr2=False, on_crash=False, autodump_interval=0.05)
        try:
            deadline = time.monotonic() + 5
            path = tmp_path / f"flightrec-{os.getpid()}.jsonl"
            while time.monotonic() < deadline and not path.exists():
                time.sleep(0.02)
            assert path.exists()
            rows = [json.loads(line) for line in path.read_text().splitlines()]
            assert rows[0]["reason"] == "periodic"
        finally:
            fr.close()


# ---------------------------------------------------------------------------
# /debug/events (EventRecorder read-side staleness fix)
# ---------------------------------------------------------------------------


class TestDebugEvents:
    def test_recent_resorts_aggregated_events_newest_first(self):
        from kubernetes_tpu.core.tracing import EventRecorder

        rec = EventRecorder()
        rec.eventf("default/a", "Warning", "FailedScheduling", "no fit")
        rec.eventf("default/b", "Normal", "Scheduled", "assigned b")
        # aggregate re-fires for a: its timestamp moves PAST b's, but the
        # deque insertion order still has a first — the staleness bug
        rec.eventf("default/a", "Warning", "FailedScheduling", "still no fit")
        recent = rec.recent()
        assert [e.object_key for e in recent] == ["default/a", "default/b"]
        assert recent[0].count == 2
        only_b = rec.recent("default/b")
        assert len(only_b) == 1 and only_b[0].reason == "Scheduled"

    def test_debug_events_endpoint_serves_recorder(self):
        from urllib.request import urlopen

        from kubernetes_tpu.core.server import SchedulerServer

        cs = FakeClientset()
        s = Scheduler(clientset=cs, deterministic_ties=True)
        cs.create_node(_node("n0"))
        cs.create_pod(_pod("p0"))
        cs.create_pod(_pod("huge", cpu="64"))
        s.run_until_idle()
        srv = SchedulerServer(s)
        port = srv.serve(0)
        try:
            body = json.loads(urlopen(
                f"http://127.0.0.1:{port}/debug/events", timeout=5).read())
            assert {e["reason"] for e in body} >= {"Scheduled",
                                                   "FailedScheduling"}
            # newest-first: the repeatedly re-aggregated FailedScheduling
            # (huge requeues) must sort to the top despite older insertion
            assert body[0]["timestamp"] >= body[-1]["timestamp"]
            one = json.loads(urlopen(
                f"http://127.0.0.1:{port}/debug/events?object=default/p0",
                timeout=5).read())
            assert one and all(e["object"] == "default/p0" for e in one)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# trace analyzer CLI (golden output on a recorded fixture trace)
# ---------------------------------------------------------------------------


def _fixture_spans(tmp_path):
    """A hand-recorded 2-process fixture: one complete bound-pod trace with
    a cross-shard conflict, one incomplete trace."""
    t0 = 1000.0
    tid = trace_id_for("fixture-pod")
    shard = [
        {"trace": tid, "span": "1.1", "parent": "", "name": "queue.admission",
         "proc": "shard-0", "pid": 1, "ts": t0, "dur": 0.0, "attrs": {}},
        {"trace": tid, "span": "1.2", "parent": "", "name": "queue.wait",
         "proc": "shard-0", "pid": 1, "ts": t0, "dur": 0.010, "attrs": {}},
        {"trace": tid, "span": "1.3", "parent": "", "name": "bind.conflict",
         "proc": "shard-0", "pid": 1, "ts": t0 + 0.012, "dur": 0.0,
         "attrs": {"node": "n3", "reason": "already_bound"}},
        {"trace": tid, "span": "1.4", "parent": "", "name": "host.commit",
         "proc": "shard-0", "pid": 1, "ts": t0 + 0.050, "dur": 0.002,
         "attrs": {}},
        {"trace": tid, "span": "1.5", "parent": "", "name": "bind.post",
         "proc": "shard-0", "pid": 1, "ts": t0 + 0.052, "dur": 0.003,
         "attrs": {"bulk": 2}},
        {"trace": tid, "span": "1.6", "parent": "", "name": "pod.e2e",
         "proc": "shard-0", "pid": 1, "ts": t0, "dur": 0.056, "attrs": {}},
        {"trace": trace_id_for("incomplete"), "span": "1.7", "parent": "",
         "name": "queue.wait", "proc": "shard-0", "pid": 1, "ts": t0,
         "dur": 0.001, "attrs": {}},
    ]
    api = [
        {"trace": tid, "span": "2.1", "parent": "", "name": "api.bind",
         "proc": "apiserver", "pid": 2, "ts": t0 + 0.053, "dur": 0.001,
         "attrs": {"node": "n5", "code": 200}},
        {"trace": tid, "span": "2.2", "parent": "", "name": "wal.append",
         "proc": "apiserver", "pid": 2, "ts": t0 + 0.0535, "dur": 0.0005,
         "attrs": {"rv": 7}},
        {"trace": tid, "span": "2.3", "parent": "", "name": "bound.fanout",
         "proc": "apiserver", "pid": 2, "ts": t0 + 0.054, "dur": 0.0002,
         "attrs": {"watchers": 2}},
    ]
    write_jsonl(str(tmp_path / "spans-shard0.jsonl"), shard)
    write_jsonl(str(tmp_path / "spans-api.jsonl"), api)
    return tid


class TestAnalyzerCLI:
    def test_golden_report_on_fixture_trace(self, tmp_path):
        from kubernetes_tpu import trace as trace_mod

        tid = _fixture_spans(tmp_path)
        buf = io.StringIO()
        rc = trace_mod.main([str(tmp_path), "--critical-paths", "1"], out=buf)
        assert rc == 0
        out = buf.getvalue()
        # merged across both processes
        assert "2 process(es): apiserver, shard-0" in out
        # completeness: 1 bound trace, complete core chain
        assert "complete chains: 1/1 bound traces (100.0%)" in out
        # per-stage table with pipeline ordering and p50/p95/p99 columns
        assert "per-stage latency (ms):" in out
        assert out.index("queue.wait") < out.index("bind.post") \
            < out.index("wal.append")
        # conflict timeline: who lost which node, and the wait→retry cost
        assert "shard-0 lost n3 (already_bound)" in out
        assert "rebound after" in out
        # critical path breakdown names the trace and its stages in order
        assert f"trace {tid}" in out
        assert "[apiserver]" in out and "[shard-0]" in out

    def test_json_summary_and_chrome_trace_export(self, tmp_path):
        from kubernetes_tpu import trace as trace_mod

        _fixture_spans(tmp_path)
        out_json = tmp_path / "chrome.json"
        buf = io.StringIO()
        rc = trace_mod.main([str(tmp_path), "--json",
                             "--chrome-trace", str(out_json)], out=buf)
        assert rc == 0
        summary = json.loads(buf.getvalue())
        assert summary["completeness"]["complete_chains"] == 1
        assert summary["stages"]["queue.wait"]["count"] == 2
        assert summary["conflicts"][0]["retry_cost_s"] > 0
        chrome = json.loads(out_json.read_text())
        assert chrome["traceEvents"]
        assert {e["ph"] for e in chrome["traceEvents"]} == {"X", "M"}
        names = {e["args"]["name"] for e in chrome["traceEvents"]
                 if e["ph"] == "M"}
        assert names == {"shard-0", "apiserver"}

    def test_cli_module_entrypoint(self, tmp_path):
        import subprocess

        _fixture_spans(tmp_path)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.trace", str(tmp_path)],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        assert "per-stage latency" in proc.stdout
        empty = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.trace",
             str(tmp_path / "nothing-here")],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
        assert empty.returncode == 1

    def test_flightrec_artifacts_load_as_spans(self, tmp_path, tracer):
        """load_spans must accept flight-recorder artifacts (kind-tagged
        rows, non-span rows skipped) and torn final lines."""
        from kubernetes_tpu import trace as trace_mod

        tracer.record("queue.wait", tracer.context_for("u1"), 0.001)
        fr = FlightRecorder(str(tmp_path), tracer=tracer)
        fr.dump("test")
        # torn tail: a crash can cut a line mid-write
        with open(fr.path, "a") as f:
            f.write('{"kind": "span", "trace": "tr')
        spans_loaded = trace_mod.load_spans([str(tmp_path)])
        assert len(spans_loaded) == 1
        assert spans_loaded[0]["name"] == "queue.wait"
