"""Descheduler (PR 20): what-if scorer bit-parity, strategies, gang-whole
and hysteresis gating, controller lifecycle, fleet-spec wiring.

The load-bearing claim is determinism: a standby manager re-deriving a
dead ACTIVE's plan must mint the SAME ``uid@node`` intent set, so the
exactly-once eviction ledger absorbs the replay. Everything here feeds
that — bit-identical host/device scoring, uid-ordered tie-breaks,
identical plans from identical snapshots.
"""

import numpy as np
import pytest
from urllib.error import HTTPError

from kubernetes_tpu.controllers.descheduler import (
    BLOCK_REASONS, DeschedulerController, DuplicateReplicas,
    LowNodeUtilization, Snapshot, TaintViolation, clears_hysteresis,
    default_strategies)
from kubernetes_tpu.core import FakeClientset
from kubernetes_tpu.core.node_info import NodeInfo, PodInfo
from kubernetes_tpu.ops import whatif
from kubernetes_tpu.testing import make_node, make_pod


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


class EvictingClientset(FakeClientset):
    """FakeClientset + the eviction subresource contract the descheduler
    funnel needs: intent-ledgered exactly-once, replay -> already=True,
    node mismatch -> 409, eviction = unbind-to-pending (the real server
    deletes + recreates pending; reading cs.pods directly the effect is
    the same: node_name clears, uid survives)."""

    def __init__(self):
        super().__init__()
        self.eviction_ledger = {}          # uid -> intent
        self.evictions_committed = 0

    def evict_pod(self, uid, node, intent):
        pod = self.pods.get(uid)
        if pod is None:
            raise HTTPError("", 404, "gone", {}, None)
        if self.eviction_ledger.get(uid) == intent:
            return {"evicted": True, "already": True}
        if not pod.node_name:
            return {"evicted": False, "pending": True}
        if pod.node_name != node:
            raise HTTPError("", 409, "NodeMismatch", {}, None)
        self.eviction_ledger[uid] = intent
        pod.node_name = ""
        self.evictions_committed += 1
        return {"evicted": True}


def _cluster(n_nodes=4, cpu="8", pods_on_first=6, pod_cpu="1"):
    """n_nodes identical nodes; `pods_on_first` pods piled on node 0."""
    cs = EvictingClientset()
    for i in range(n_nodes):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": cpu, "memory": "16Gi",
                                  "pods": 32}).obj())
    for i in range(pods_on_first):
        p = make_pod().name(f"p{i}").uid(f"p{i}").req({"cpu": pod_cpu}).obj()
        cs.create_pod(p)
        cs.bind(p, "n0")
    return cs


def _snapshot_of(cs) -> Snapshot:
    nodes = sorted(cs.nodes.values(), key=lambda n: n.name)
    infos = [NodeInfo(n) for n in nodes]
    row = {ni.name: i for i, ni in enumerate(infos)}
    bound = sorted((p for p in cs.pods.values()
                    if p.node_name in row and p.deletion_ts is None),
                   key=lambda p: p.uid)
    gangs = {}
    for p in bound:
        infos[row[p.node_name]].add_pod(PodInfo.of(p))
        if p.pod_group:
            gangs.setdefault(p.pod_group, []).append(p)
    return Snapshot(infos, row, bound, gangs)


def _random_batch(rng, n_nodes, n_pods, n_res=3) -> whatif.WhatIfBatch:
    alloc_r = rng.integers(0, 64_000, (n_nodes, n_res)).astype(np.int64)
    alloc_pods = rng.integers(1, 40, n_nodes).astype(np.int64)
    req_r = np.minimum(
        rng.integers(0, 48_000, (n_nodes, n_res)).astype(np.int64), alloc_r)
    nonzero = np.maximum(req_r[:, :2], 1)
    pod_count = rng.integers(0, 20, n_nodes).astype(np.int64)
    request = rng.integers(0, 8_000, (n_pods, n_res)).astype(np.int64)
    nz_request = np.maximum(request[:, :2], 100)
    src = rng.integers(0, n_nodes, n_pods).astype(np.int64)
    mask = rng.random((n_pods, n_nodes)) < 0.9
    return whatif.WhatIfBatch(alloc_r, alloc_pods, req_r, nonzero,
                              pod_count, request, nz_request, src, mask)


# ---------------------------------------------------------------------------
# what-if scorer
# ---------------------------------------------------------------------------


def test_whatif_host_device_bit_parity_fuzz():
    """The acceptance contract: host walker and jitted device mirror are
    bit-identical on fuzzed batches — fit masks AND int64 scores. Padding
    to power-of-two tiers must never leak into the sliced-back result."""
    rng = np.random.default_rng(0xD35C)
    for _ in range(12):
        n_nodes = int(rng.integers(1, 40))
        n_pods = int(rng.integers(1, 20))
        b = _random_batch(rng, n_nodes, n_pods)
        fit_h, sc_h = whatif.whatif_scores(b, device=False)
        fit_d, sc_d = whatif.whatif_scores(b, device=True)
        np.testing.assert_array_equal(fit_h, fit_d)
        np.testing.assert_array_equal(sc_h, sc_d)
        assert sc_h.dtype == np.int64


def test_whatif_empty_batch():
    b = whatif.encode_batch([], [])
    fit, sc = whatif.whatif_scores(b)
    assert fit.shape == (0, 0) and sc.shape == (0, 0)
    assert whatif.best_moves(b, fit, sc) == []


def test_encode_batch_masks_taints_and_unschedulable():
    tainted = make_node().name("bad").capacity({"cpu": "8", "pods": 10}) \
        .taint("dedicated", "infra").obj()
    cordoned = make_node().name("cordon").capacity(
        {"cpu": "8", "pods": 10}).unschedulable().obj()
    clean = make_node().name("ok").capacity({"cpu": "8", "pods": 10}).obj()
    infos = [NodeInfo(n) for n in (tainted, cordoned, clean)]
    plain = make_pod().name("plain").req({"cpu": "1"}).node("ok").obj()
    tol = make_pod().name("tol").req({"cpu": "1"}).node("ok") \
        .toleration("dedicated", "infra").obj()
    b = whatif.encode_batch(infos, [plain, tol])
    # rows: 0=tainted, 1=cordoned, 2=clean
    assert list(b.mask[0]) == [False, False, True]
    assert list(b.mask[1]) == [True, False, True]


def test_encode_batch_row_encoding_and_nonzero_defaults():
    n = make_node().name("n0").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
    ni = NodeInfo(n)
    bound = make_pod().name("b0").req({"cpu": "1"}).node("n0").obj()
    ni.add_pod(PodInfo.of(bound))
    zero = make_pod().name("z0").node("n0").obj()   # no explicit request
    b = whatif.encode_batch([ni], [zero])
    assert b.alloc_r[0, whatif.SLOT_CPU] == 4000
    assert b.alloc_pods[0] == 10
    assert b.req_r[0, whatif.SLOT_CPU] == 1000
    assert b.pod_count[0] == 1
    # zero-request candidates score with the scheduler's non-zero defaults
    assert b.nz_request[0, 0] == NodeInfo.DEFAULT_MILLI_CPU
    assert b.nz_request[0, 1] == NodeInfo.DEFAULT_MEMORY


def test_best_moves_tie_breaks_to_lowest_row():
    """Equal-scored landing rows pick the LOWEST index on every manager —
    the determinism the exactly-once replay depends on."""
    fit = np.ones((1, 4), bool)
    score = np.array([[10, 50, 50, 50]], np.int64)
    b = whatif.WhatIfBatch(*[None] * 5, np.zeros((1, 3), np.int64),
                           np.zeros((1, 2), np.int64),
                           np.array([0], np.int64), fit)
    (mv,) = whatif.best_moves(b, fit, score)
    assert (mv.src, mv.dst, mv.improvement) == (0, 1, 40)


def test_best_moves_unfit_source_scores_current_minus_one():
    """Drift shrank the node under a bound pod: its seat no longer fits,
    so a merely-equal landing still registers a positive improvement."""
    fit = np.array([[False, True]])
    score = np.array([[50, 50]], np.int64)
    b = whatif.WhatIfBatch(*[None] * 5, np.zeros((1, 3), np.int64),
                           np.zeros((1, 2), np.int64),
                           np.array([0], np.int64), fit)
    (mv,) = whatif.best_moves(b, fit, score)
    assert mv.dst == 1 and mv.improvement == 1


def test_best_moves_no_feasible_other_row_is_none():
    fit = np.array([[True, False]])
    score = np.array([[50, 99]], np.int64)
    b = whatif.WhatIfBatch(*[None] * 5, np.zeros((1, 3), np.int64),
                           np.zeros((1, 2), np.int64),
                           np.array([0], np.int64), fit)
    assert whatif.best_moves(b, fit, score) == [None]


# ---------------------------------------------------------------------------
# hysteresis + strategies
# ---------------------------------------------------------------------------


def test_clears_hysteresis():
    assert clears_hysteresis(5, 5)
    assert not clears_hysteresis(4, 5)
    # must_move (illegal seat) waives the floor, even negative improvement
    assert clears_hysteresis(-3, 5, must_move=True)


def test_low_node_utilization_nominates_largest_first():
    cs = _cluster(n_nodes=3, pods_on_first=0)
    sizes = {"pa": "4", "pb": "1", "pc": "2"}
    for name, cpu in sizes.items():
        p = make_pod().name(name).uid(name).req({"cpu": cpu}).obj()
        cs.create_pod(p)
        cs.bind(p, "n0")
    snap = _snapshot_of(cs)
    got = LowNodeUtilization(margin=0.10, per_node=2).candidates(snap)
    assert [p.uid for p in got] == ["pa", "pc"]   # largest first, capped


def test_duplicate_replicas_keeps_lowest_uid():
    cs = _cluster(n_nodes=2, pods_on_first=0)
    for name in ("r2", "r0", "r1"):
        p = make_pod().name(name).uid(name).req({"cpu": "1"}) \
            .labels({"app": "web"}).obj()
        cs.create_pod(p)
        cs.bind(p, "n0")
    lone = make_pod().name("solo").uid("solo").req({"cpu": "1"}) \
        .labels({"app": "web"}).obj()
    cs.create_pod(lone)
    cs.bind(lone, "n1")
    got = DuplicateReplicas().candidates(_snapshot_of(cs))
    assert sorted(p.uid for p in got) == ["r1", "r2"]


def test_taint_violation_detects_untolerated_seat():
    cs = _cluster(n_nodes=2, pods_on_first=1)
    # churn re-registered n0 with a taint the bound pod never tolerated
    tainted = make_node().name("n0").capacity(
        {"cpu": "8", "memory": "16Gi", "pods": 32}) \
        .taint("maintenance", "true", "NoExecute").obj()
    cs.update_node(tainted)
    strat = TaintViolation()
    got = strat.candidates(_snapshot_of(cs))
    assert [p.uid for p in got] == ["p0"]
    assert strat.must_move


def test_default_strategies_order_is_violations_first():
    names = [s.name for s in default_strategies()]
    assert names == ["taint-violation", "duplicate-replicas",
                     "low-node-utilization"]


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


def test_controller_converges_imbalanced_cluster():
    """6 pods piled on one of 4 nodes: reconcile ticks drain the hot node
    through the eviction funnel until the spread repairs (evicted pods go
    pending; stddev over the remaining bound set falls monotonically to
    the empty fixpoint here — rebinding is the scheduler's job)."""
    cs = _cluster()
    ctrl = DeschedulerController(
        cs, strategies=[LowNodeUtilization()], hysteresis=1,
        primary_qps=1000.0, burst=16.0)
    before = None
    for _ in range(8):
        ctrl.tick_once()
        if before is None:
            before = ctrl.util_stddev_milli
        if not any(p.node_name for p in cs.pods.values()):
            break
    assert ctrl.active and ctrl.takeovers == 1
    assert cs.evictions_committed > 0
    assert sum(ctrl.moves_total.values()) == cs.evictions_committed
    assert before > 0
    # every committed eviction is ledgered under its deterministic intent
    for uid, intent in cs.eviction_ledger.items():
        assert intent == f"{uid}@n0"
        assert ctrl.planned_intents[uid] == intent


def test_two_managers_plan_identical_intents():
    """The failover contract, minus the processes: two managers over
    identical snapshots derive the same uid@node intent map."""
    plans = []
    for _ in range(2):
        cs = _cluster()
        ctrl = DeschedulerController(
            cs, strategies=[LowNodeUtilization()], hysteresis=1)
        ctrl.reconcile_once()
        plans.append(dict(ctrl.planned_intents))
    assert plans[0] == plans[1] and plans[0]


def test_replayed_intent_counts_already_not_double_evict():
    cs = _cluster()
    ctrl = DeschedulerController(
        cs, strategies=[LowNodeUtilization()], hysteresis=1,
        primary_qps=1000.0, burst=16.0)
    ctrl.tick_once()
    first = cs.evictions_committed
    assert first > 0
    # replay the exact intents (the standby's duplicate emission)
    for uid, intent in list(cs.eviction_ledger.items()):
        got = cs.evict_pod(uid, intent.split("@", 1)[1], intent)
        assert got == {"evicted": True, "already": True}
    assert cs.evictions_committed == first


def test_hysteresis_floor_blocks_churn_moves():
    cs = _cluster()
    ctrl = DeschedulerController(
        cs, strategies=[LowNodeUtilization()], hysteresis=10_000)
    ctrl.reconcile_once()
    assert sum(ctrl.moves_total.values()) == 0
    assert ctrl.blocked_total["hysteresis"] > 0
    assert cs.evictions_committed == 0


def test_gang_moves_whole_or_not_at_all():
    """One member with no feasible landing pins the entire PodGroup."""
    cs = _cluster(n_nodes=2, pods_on_first=0)
    # n1 is tainted: the gang's pods (no tolerations) have nowhere to go
    tainted = make_node().name("n1").capacity(
        {"cpu": "8", "memory": "16Gi", "pods": 32}) \
        .taint("dedicated", "infra").obj()
    cs.update_node(tainted)
    for i in range(3):
        p = make_pod().name(f"g{i}").uid(f"g{i}").req({"cpu": "2"}).obj()
        p.pod_group = "team"
        cs.create_pod(p)
        cs.bind(p, "n0")
    ctrl = DeschedulerController(
        cs, strategies=[LowNodeUtilization()], hysteresis=1)
    ctrl.reconcile_once()
    assert cs.evictions_committed == 0
    assert ctrl.blocked_total["gang"] >= 1
    assert all(p.node_name == "n0" for p in cs.pods.values())


def test_gang_with_feasible_landings_moves_every_member():
    cs = _cluster(n_nodes=3, pods_on_first=0)
    for i in range(2):
        p = make_pod().name(f"g{i}").uid(f"g{i}").req({"cpu": "3"}).obj()
        p.pod_group = "team"
        cs.create_pod(p)
        cs.bind(p, "n0")
    ctrl = DeschedulerController(
        cs, strategies=[LowNodeUtilization()], hysteresis=1,
        primary_qps=1000.0, burst=16.0)
    ctrl.tick_once()
    assert cs.evictions_committed == 2
    assert ctrl.blocked_total["gang"] == 0
    assert all(not p.node_name for p in cs.pods.values())


def test_standby_idles_until_lease_expires_then_takes_over():
    cs = _cluster(pods_on_first=0)
    clock = {"t": 100.0}
    cs.lease_now = lambda: clock["t"]
    a = DeschedulerController(cs, identity="dm-0", lease_ttl=2.0,
                              now=lambda: clock["t"])
    b = DeschedulerController(cs, identity="dm-1", lease_ttl=2.0,
                              now=lambda: clock["t"])
    a.tick_once()
    b.tick_once()
    assert a.active and not b.active and b.standby_ticks == 1
    clock["t"] += 5.0           # dm-0 dies: its lease expires
    b.tick_once()
    assert b.active and b.takeovers == 1


def test_must_move_strategy_waives_hysteresis():
    """A taint-violating seat moves even under a floor that blocks every
    utilization move — the seat is illegal, staying is not an option."""
    cs = _cluster(n_nodes=2, pods_on_first=1)
    tainted = make_node().name("n0").capacity(
        {"cpu": "8", "memory": "16Gi", "pods": 32}) \
        .taint("maintenance", "true", "NoExecute").obj()
    cs.update_node(tainted)
    ctrl = DeschedulerController(cs, hysteresis=10_000,
                                 primary_qps=1000.0, burst=16.0)
    ctrl.tick_once()
    assert cs.evictions_committed == 1
    assert ctrl.moves_total["taint-violation"] == 1


def test_metrics_text_carries_every_series():
    cs = _cluster()
    ctrl = DeschedulerController(cs, strategies=[LowNodeUtilization()],
                                 hysteresis=1, primary_qps=1000.0,
                                 burst=16.0)
    ctrl.tick_once()
    text = ctrl.metrics_text()
    for series in ("descheduler_moves_total{strategy=",
                   "descheduler_whatif_batch_duration_seconds_sum",
                   "descheduler_whatif_batch_duration_seconds_count",
                   "descheduler_drift_candidates{strategy=",
                   "descheduler_ticks_total",
                   "descheduler_util_stddev_milli",
                   "descheduler_manager_active 1"):
        assert series in text, series
    for reason in BLOCK_REASONS:
        assert f'descheduler_moves_blocked_total{{reason="{reason}"}}' \
            in text


def test_stats_shape():
    cs = _cluster(pods_on_first=0)
    ctrl = DeschedulerController(cs)
    ctrl.tick_once()
    st = ctrl.stats()
    for key in ("identity", "active", "ticks", "moves", "blocked",
                "planned_intents", "whatif_batches", "drift",
                "util_stddev_milli", "evictions_total",
                "evictions_replayed", "pending_evictions"):
        assert key in st, key


# ---------------------------------------------------------------------------
# fleet wiring
# ---------------------------------------------------------------------------


def test_fleet_spec_deschedule_round_trip_and_validate():
    from kubernetes_tpu.fleet import FleetSpec

    spec = FleetSpec.from_dict({
        "deschedule": {"managers": 2, "lease_ttl": 1.5, "tick": 0.25,
                       "hysteresis": 7, "max_moves": 32}})
    assert spec.deschedule["hysteresis"] == 7
    again = FleetSpec.from_dict(spec.to_dict())
    assert again.deschedule == spec.deschedule
    spec.validate()
    with pytest.raises(ValueError, match="deschedule.managers"):
        FleetSpec.from_dict({"deschedule": {"managers": 0}}).validate()


def test_controllers_package_exports():
    from kubernetes_tpu import controllers

    for name in ("DeschedulerController", "LowNodeUtilization",
                 "DuplicateReplicas", "TaintViolation",
                 "clears_hysteresis"):
        assert hasattr(controllers, name)
