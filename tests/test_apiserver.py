"""REST + watch apiserver (core/apiserver.py): the scheduler runs against a
REAL process boundary — JSON on the wire, a reflector thread feeding the
informer cache — and produces the SAME assignments as the in-process run
(client-go reflector.go:470 / shared_informer.go:841 seam; apiserver REST
surface reduced to the scheduler's verbs)."""

import time

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset
from kubernetes_tpu.models import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes():
    out = []
    for i in range(12):
        b = (make_node().name(f"n{i}")
             .capacity({"cpu": "8", "memory": "16Gi", "pods": 110})
             .zone(f"z{i % 3}"))
        if i % 5 == 0:
            b = b.taint("dedicated", "infra", "NoSchedule")
        out.append(b.obj())
    return out


def _pods(n):
    proto = (make_pod().name("proto").req({"cpu": "500m", "memory": "256Mi"})
             .labels({"app": "wire"}).obj())
    return [proto.clone_from_template(f"p{i}") for i in range(n)]


def test_scheduler_over_the_wire_matches_in_process():
    # in-process oracle
    cs_h = FakeClientset()
    host = Scheduler(clientset=cs_h, deterministic_ties=True)
    for node in _nodes():
        cs_h.create_node(node)
    ph = _pods(40)
    for p in ph:
        cs_h.create_pod(p)
    host.run_until_idle()

    # over the wire: apiserver process boundary + reflector-fed scheduler
    api = APIServer()
    port = api.serve(0)
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    sched = TPUScheduler(clientset=client)
    for node in _nodes():
        client.create_node(node)
    pw = _pods(40)
    for p in pw:
        client.create_pod(p)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and sched.scheduled < 40:
        sched.run_until_idle()
        time.sleep(0.005)

    # bindings land in the SERVER's store via the binding subresource
    hb = sorted(cs_h.bindings.values())
    wb = sorted(api.store.bindings.values())
    assert sched.scheduled == 40
    assert wb == hb
    # per-pod equality by name (uids differ across the two runs)
    h_by_name = {cs_h.pods[u].name: n for u, n in cs_h.bindings.items()}
    w_by_name = {api.store.pods[u].name: n for u, n in api.store.bindings.items()}
    assert h_by_name == w_by_name
    client.close()
    api.shutdown()


def test_watch_stream_delivers_deletes():
    api = APIServer()
    port = api.serve(0)
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    sched = TPUScheduler(clientset=client)
    client.create_node(make_node().name("n0")
                       .capacity({"cpu": "4", "pods": 10}).obj())
    p = make_pod().name("doomed").req({"cpu": "1"}).obj()
    client.create_pod(p)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and sched.scheduled < 1:
        sched.run_until_idle()
        time.sleep(0.005)
    assert sched.scheduled == 1
    bound = api.store.pods[list(api.store.bindings)[0]]
    client.delete_pod(bound)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and bound.uid in client.pods:
        sched.run_until_idle()
        time.sleep(0.005)
    assert bound.uid not in client.pods  # reflector saw the DELETED event
    sched.run_until_idle()
    assert sched.cache.nodes["n0"].pods == [] or all(
        pi.pod.uid != bound.uid for pi in sched.cache.nodes["n0"].pods)
    client.close()
    api.shutdown()


def test_wire_codec_preserves_scheduling_spec():
    """Round-trip of affinity / spread / gates / host ports / claims — the
    codec must not silently drop scheduling-relevant spec (a gated pod must
    stay gated over the wire, host ports must conflict, anti-affinity must
    spread)."""
    api = APIServer()
    port = api.serve(0)
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    sched = TPUScheduler(clientset=client)
    for i in range(4):
        client.create_node(make_node().name(f"n{i}")
                           .capacity({"cpu": "8", "pods": 20})
                           .zone(f"z{i % 2}").obj())

    gated = (make_pod().name("gated").req({"cpu": "1"})
             .scheduling_gate("wait-for-it").obj())
    client.create_pod(gated)
    anti = []
    for i in range(3):
        p = (make_pod().name(f"anti-{i}").labels({"app": "a"})
             .pod_affinity("kubernetes.io/hostname", {"app": "a"}, anti=True)
             .req({"cpu": "500m"}).obj())
        client.create_pod(p)
        anti.append(p)
    ports = []
    for i in range(2):
        p = make_pod().name(f"hp-{i}").req({"cpu": "100m"}).host_port(8080).obj()
        client.create_pod(p)
        ports.append(p)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and sched.scheduled < 5:
        sched.run_until_idle()
        time.sleep(0.005)

    by_name = {api.store.pods[u].name: n
               for u, n in api.store.bindings.items()}
    assert "gated" not in by_name                       # gate survived the wire
    anti_nodes = [by_name[f"anti-{i}"] for i in range(3)]
    assert len(set(anti_nodes)) == 3                    # anti-affinity spread
    hp_nodes = [by_name[f"hp-{i}"] for i in range(2)]
    assert len(set(hp_nodes)) == 2                      # host-port conflict
    client.close()
    api.shutdown()


def test_reflector_relists_after_server_restart():
    """client-go reflector semantics (reflector.go:470): when the watch
    stream dies, the client re-connects and re-lists; objects that vanished
    during the outage are dispatched DELETED at the SYNC barrier, new
    objects ADDED — the informer cache converges on the restarted server's
    truth instead of freezing forever (round-4 advisor finding)."""
    api = APIServer()
    port = api.serve(0)
    api.store.create_node(make_node().name("n0")
                          .capacity({"cpu": "4", "pods": 10}).obj())
    ghost = make_pod().name("ghost").req({"cpu": "1"}).obj()
    api.store.create_pod(ghost)
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    assert ghost.uid in client.pods and "n0" in client.nodes

    # Server restarts: the ghost pod is gone, a new node exists.
    api.shutdown()
    api2 = APIServer()
    api2.store.create_node(make_node().name("n0")
                           .capacity({"cpu": "4", "pods": 10}).obj())
    api2.store.create_node(make_node().name("n1")
                           .capacity({"cpu": "4", "pods": 10}).obj())
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            api2.serve(port)
            break
        except OSError:
            time.sleep(0.1)  # TIME_WAIT on the old socket

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and (
            ghost.uid in client.pods or "n1" not in client.nodes):
        time.sleep(0.02)
    assert ghost.uid not in client.pods   # Replace barrier delivered delete
    assert "n1" in client.nodes           # re-list delivered the new node
    assert "n0" in client.nodes
    client.close()
    api2.shutdown()


def test_dead_initial_connection_raises():
    """A clientset whose FIRST connection fails must raise, not return a
    silently empty informer cache (round-4 advisor finding)."""
    import pytest
    with pytest.raises((ConnectionError, TimeoutError)):
        HTTPClientset("http://127.0.0.1:1", sync_timeout=5.0)


def _json_call(base, method, path, body=None):
    import json as _json
    from urllib import request as _rq
    data = _json.dumps(body).encode() if body is not None else None
    req = _rq.Request(base + path, data=data, method=method,
                      headers={"Content-Type": "application/json"})
    with _rq.urlopen(req, timeout=30) as resp:
        raw = resp.read()
    return _json.loads(raw) if raw else None


def test_pod_groups_over_the_wire_gate_gangs_and_replay():
    """Gang state over the real HTTP LIST/watch (PR-16 satellite): a
    PodGroup created through one clientset gates the gang on a scheduler
    reading through ANOTHER clientset — the all-or-nothing cycle holds
    across the process boundary, a late subscriber gets the group from
    LIST replay, and the arrival of the final member (over the wire)
    releases the whole gang."""
    from kubernetes_tpu.api.types import PodGroup

    api = APIServer()
    port = api.serve(0)
    base = f"http://127.0.0.1:{port}"
    writer = HTTPClientset(base)
    reader = HTTPClientset(base)
    sched = Scheduler(clientset=reader, deterministic_ties=True)
    try:
        for i in range(3):
            writer.create_node(make_node().name(f"n{i}")
                               .capacity({"cpu": 4, "memory": "8Gi",
                                          "pods": 10}).obj())
        writer.create_pod_group(PodGroup(name="gang", min_count=3))
        pods = []
        for i in range(2):
            p = make_pod().name(f"gang-{i}").req({"cpu": "1"}).obj()
            p.pod_group = "gang"
            pods.append(p)
            writer.create_pod(p)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and (
                len(reader.pods) < 2 or len(reader.nodes) < 3
                or "default/gang" not in reader.pod_groups):
            time.sleep(0.02)
        # the group crossed the wire: the reading scheduler must hold the
        # gang (2 of 3 members present -> nothing schedules)
        assert reader.pod_groups["default/gang"].min_count == 3
        sched.run_until_idle()
        assert not api.store.bindings
        # a LATE subscriber sees the group via LIST replay, no watch race
        late = HTTPClientset(base)
        try:
            assert "default/gang" in late.pod_groups
            assert late.pod_groups["default/gang"].min_count == 3
        finally:
            late.close()
        # the final member arrives over the wire: whole gang releases
        p3 = make_pod().name("gang-2").req({"cpu": "1"}).obj()
        p3.pod_group = "gang"
        writer.create_pod(p3)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(api.store.bindings) < 3:
            sched.run_until_idle()
            time.sleep(0.02)
        assert len(api.store.bindings) == 3
        assert set(api.store.bindings) == {p.uid for p in pods} | {p3.uid}
    finally:
        writer.close()
        reader.close()
        api.shutdown()


def test_flow_admin_endpoint_reweights_live():
    """/flow (PR-16 satellite): GET exposes per-level weights + admission
    counters; POST re-weights one level's flows live (applied under the
    flow controller's own lock). Unknown level -> 404; the exempt lane and
    non-positive weights -> 400."""
    api = APIServer()
    port = api.serve(0)
    base = f"http://127.0.0.1:{port}"
    try:
        got = _json_call(base, "GET", "/flow")
        assert "workload" in got["weights"] and "workload" in got["levels"]
        # live re-weight: starve down a flood tenant mid-storm
        got = _json_call(base, "POST", "/flow",
                         {"level": "workload",
                          "weights": {"tenant-flood": 0.25,
                                      "tenant-gold": 4.0}})
        assert got["weights"]["tenant-flood"] == 0.25
        again = _json_call(base, "GET", "/flow")
        assert again["weights"]["workload"]["tenant-flood"] == 0.25
        assert again["weights"]["workload"]["tenant-gold"] == 4.0
        # the write plane still admits (the re-weight never touched the
        # write lock, but prove the server is alive and serving writes)
        cs = HTTPClientset(base)
        try:
            cs.create_node(make_node().name("n0")
                           .capacity({"cpu": 4, "pods": 10}).obj())
            assert "n0" in api.store.nodes
        finally:
            cs.close()
        import pytest
        from urllib.error import HTTPError
        with pytest.raises(HTTPError) as e:
            _json_call(base, "POST", "/flow",
                       {"level": "nope", "weights": {"t": 1.0}})
        assert e.value.code == 404
        with pytest.raises(HTTPError) as e:
            _json_call(base, "POST", "/flow",
                       {"level": "exempt", "weights": {"t": 1.0}})
        assert e.value.code == 400
        with pytest.raises(HTTPError) as e:
            _json_call(base, "POST", "/flow",
                       {"level": "workload", "weights": {"t": 0.0}})
        assert e.value.code == 400
    finally:
        api.shutdown()
