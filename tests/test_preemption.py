"""Preemption: DefaultPreemption PostFilter + dry-run Evaluator
(reference framework/preemption/preemption.go, defaultpreemption/).
"""

from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _small_cluster(sched, n=2, cpu="2"):
    for i in range(n):
        sched.clientset.create_node(
            make_node().name(f"node-{i}").capacity({"cpu": cpu, "memory": "4Gi", "pods": 10}).obj())


class TestPreemption:
    def test_high_priority_pod_preempts(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=2, cpu="2")
        # Fill both nodes with low-priority pods.
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"low-{i}").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        # High-priority pod doesn't fit anywhere → must preempt.
        high = make_pod().name("high").req({"cpu": "2"}).priority(100).obj()
        s.clientset.create_pod(high)
        s.run_until_idle()
        bound = {s.clientset.pods[u].name: n for u, n in s.clientset.bindings.items()
                 if u in s.clientset.pods}
        assert "high" in bound, f"high-priority pod not scheduled: {bound}"
        # Exactly one victim was deleted.
        remaining = {p.name for p in s.clientset.pods.values()}
        assert len(remaining & {"low-0", "low-1"}) == 1
        assert high.nominated_node_name  # nomination recorded

    def test_no_preemption_when_policy_never(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        s.clientset.create_pod(
            make_pod().name("low").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        never = make_pod().name("never").req({"cpu": "2"}).priority(100).obj()
        never.preemption_policy = "Never"
        s.clientset.create_pod(never)
        s.run_until_idle()
        assert {p.name for p in s.clientset.pods.values()} == {"low", "never"}
        assert "never" not in {
            s.clientset.pods[u].name for u in s.clientset.bindings
            if u in s.clientset.pods}

    def test_no_preemption_of_equal_priority(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        s.clientset.create_pod(
            make_pod().name("peer").req({"cpu": "2"}).priority(50).obj())
        s.run_until_idle()
        s.clientset.create_pod(
            make_pod().name("same").req({"cpu": "2"}).priority(50).obj())
        s.run_until_idle()
        assert {p.name for p in s.clientset.pods.values()} == {"peer", "same"}

    def test_minimal_victim_set(self):
        """Reprieve keeps pods that don't need to die: two 1-cpu victims,
        incoming needs 1 cpu → only one is evicted."""
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"small-{i}").req({"cpu": "1"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        s.clientset.create_pod(
            make_pod().name("high").req({"cpu": "1"}).priority(100).obj())
        s.run_until_idle()
        names = {p.name for p in s.clientset.pods.values()}
        assert "high" in names
        assert len(names & {"small-0", "small-1"}) == 1  # exactly one victim

    def test_picks_lowest_priority_victims(self):
        """Candidate selection prefers the node whose victims have the lowest
        highest-priority (pickOneNodeForPreemption)."""
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=2, cpu="2")
        s.clientset.create_pod(
            make_pod().name("mid").req({"cpu": "2"}).priority(10)
            .node_selector({}).obj())
        s.run_until_idle()
        # Force placement of second pod on the other node.
        s.clientset.create_pod(
            make_pod().name("lowest").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        s.clientset.create_pod(
            make_pod().name("high").req({"cpu": "2"}).priority(100).obj())
        s.run_until_idle()
        names = {p.name for p in s.clientset.pods.values()}
        assert "high" in names
        assert "mid" in names, "should have preempted the lowest-priority victim"
        assert "lowest" not in names

    def test_preemption_with_spread_constraints_prefilter_state(self):
        """AddPod/RemovePod PreFilter extensions keep spread state coherent
        during dry runs."""
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"node-{i}")
                .capacity({"cpu": "2", "memory": "4Gi", "pods": 10})
                .zone(f"z{i}").obj())
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"low-{i}").req({"cpu": "2"}).priority(1)
                .labels({"app": "w"}).obj())
        s.run_until_idle()
        p = (make_pod().name("spread").req({"cpu": "1"}).priority(100)
             .labels({"app": "w"})
             .spread_constraint(1, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "w"}).obj())
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert "spread" in {
            s.clientset.pods[u].name for u in s.clientset.bindings
            if u in s.clientset.pods}
