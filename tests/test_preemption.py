"""Preemption: DefaultPreemption PostFilter + dry-run Evaluator
(reference framework/preemption/preemption.go, defaultpreemption/).
"""

from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _small_cluster(sched, n=2, cpu="2"):
    for i in range(n):
        sched.clientset.create_node(
            make_node().name(f"node-{i}").capacity({"cpu": cpu, "memory": "4Gi", "pods": 10}).obj())


class TestPreemption:
    def test_high_priority_pod_preempts(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=2, cpu="2")
        # Fill both nodes with low-priority pods.
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"low-{i}").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        # High-priority pod doesn't fit anywhere → must preempt.
        high = make_pod().name("high").req({"cpu": "2"}).priority(100).obj()
        s.clientset.create_pod(high)
        s.run_until_idle()
        bound = {s.clientset.pods[u].name: n for u, n in s.clientset.bindings.items()
                 if u in s.clientset.pods}
        assert "high" in bound, f"high-priority pod not scheduled: {bound}"
        # Exactly one victim was deleted.
        remaining = {p.name for p in s.clientset.pods.values()}
        assert len(remaining & {"low-0", "low-1"}) == 1
        assert high.nominated_node_name  # nomination recorded

    def test_no_preemption_when_policy_never(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        s.clientset.create_pod(
            make_pod().name("low").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        never = make_pod().name("never").req({"cpu": "2"}).priority(100).obj()
        never.preemption_policy = "Never"
        s.clientset.create_pod(never)
        s.run_until_idle()
        assert {p.name for p in s.clientset.pods.values()} == {"low", "never"}
        assert "never" not in {
            s.clientset.pods[u].name for u in s.clientset.bindings
            if u in s.clientset.pods}

    def test_no_preemption_of_equal_priority(self):
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        s.clientset.create_pod(
            make_pod().name("peer").req({"cpu": "2"}).priority(50).obj())
        s.run_until_idle()
        s.clientset.create_pod(
            make_pod().name("same").req({"cpu": "2"}).priority(50).obj())
        s.run_until_idle()
        assert {p.name for p in s.clientset.pods.values()} == {"peer", "same"}

    def test_minimal_victim_set(self):
        """Reprieve keeps pods that don't need to die: two 1-cpu victims,
        incoming needs 1 cpu → only one is evicted."""
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=1, cpu="2")
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"small-{i}").req({"cpu": "1"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        s.clientset.create_pod(
            make_pod().name("high").req({"cpu": "1"}).priority(100).obj())
        s.run_until_idle()
        names = {p.name for p in s.clientset.pods.values()}
        assert "high" in names
        assert len(names & {"small-0", "small-1"}) == 1  # exactly one victim

    def test_picks_lowest_priority_victims(self):
        """Candidate selection prefers the node whose victims have the lowest
        highest-priority (pickOneNodeForPreemption)."""
        s = Scheduler(deterministic_ties=True)
        _small_cluster(s, n=2, cpu="2")
        s.clientset.create_pod(
            make_pod().name("mid").req({"cpu": "2"}).priority(10)
            .node_selector({}).obj())
        s.run_until_idle()
        # Force placement of second pod on the other node.
        s.clientset.create_pod(
            make_pod().name("lowest").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        assert s.scheduled == 2
        s.clientset.create_pod(
            make_pod().name("high").req({"cpu": "2"}).priority(100).obj())
        s.run_until_idle()
        names = {p.name for p in s.clientset.pods.values()}
        assert "high" in names
        assert "mid" in names, "should have preempted the lowest-priority victim"
        assert "lowest" not in names

    def test_preemption_with_spread_constraints_prefilter_state(self):
        """AddPod/RemovePod PreFilter extensions keep spread state coherent
        during dry runs."""
        s = Scheduler(deterministic_ties=True)
        for i in range(2):
            s.clientset.create_node(
                make_node().name(f"node-{i}")
                .capacity({"cpu": "2", "memory": "4Gi", "pods": 10})
                .zone(f"z{i}").obj())
        for i in range(2):
            s.clientset.create_pod(
                make_pod().name(f"low-{i}").req({"cpu": "2"}).priority(1)
                .labels({"app": "w"}).obj())
        s.run_until_idle()
        p = (make_pod().name("spread").req({"cpu": "1"}).priority(100)
             .labels({"app": "w"})
             .spread_constraint(1, "topology.kubernetes.io/zone",
                                "DoNotSchedule", {"app": "w"}).obj())
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert "spread" in {
            s.clientset.pods[u].name for u in s.clientset.bindings
            if u in s.clientset.pods}


class TestDevicePreemptionEquivalence:
    """Batched DryRunPreemption kernel (ops/kernel.py dry_run_preemption)
    vs the host Evaluator loop: identical victims, nominations, and final
    assignments (round-4 VERDICT item 2; ref preemption.go:425,201,286)."""

    def _pair_run(self, seed, n_nodes=12, fillers=18, preemptors=4):
        import random
        from kubernetes_tpu.models.tpu_scheduler import TPUScheduler

        def populate(sched):
            rng = random.Random(seed)
            caps = []
            for i in range(n_nodes):
                cpu = rng.choice([2, 4])
                caps.append(cpu)
                b = (make_node().name(f"node-{i}")
                     .capacity({"cpu": cpu, "memory": "8Gi", "pods": 12}))
                if rng.random() < 0.2:
                    b = b.taint("team", "infra", "NoSchedule")
                sched.clientset.create_node(b.obj())
            # SATURATE every node's cpu with lower-priority fillers so the
            # preemptors must evict (each node gets cpu/2-sized pods x2).
            f_i = 0
            for i, cpu in enumerate(caps):
                for _ in range(2):
                    sched.clientset.create_pod(
                        make_pod().name(f"low-{f_i}")
                        .req({"cpu": f"{cpu * 500}m", "memory": "1Gi"})
                        .node_selector({"kubernetes.io/hostname": f"node-{i}"})
                        .toleration("team", "infra")
                        .priority(rng.choice([0, 1, 5])).obj())
                    f_i += 1
            sched.run_until_idle()
            for i in range(preemptors):
                p = (make_pod().name(f"hi-{i}")
                     .req({"cpu": "2", "memory": "2Gi"}).priority(100))
                if rng.random() < 0.5:
                    p = p.toleration("team", "infra")
                sched.clientset.create_pod(p.obj())
            for _ in range(30):
                sched.process_async_api_errors()
                if not sched.run_until_idle():
                    pass
            return sched

        host = populate(Scheduler(deterministic_ties=True))
        dev = populate(TPUScheduler())
        return host, dev

    def _state(self, sched):
        pods = {p.name: (p.node_name, p.nominated_node_name)
                for p in sched.clientset.pods.values()}
        survivors = {p.name for p in sched.clientset.pods.values()}
        return pods, survivors

    def test_fuzz_identical_victims_and_assignments(self):
        for seed in range(6):
            host, dev = self._pair_run(seed)
            h_pods, h_surv = self._state(host)
            d_pods, d_surv = self._state(dev)
            assert h_surv == d_surv, (
                f"seed {seed}: victim sets diverged "
                f"host-only={h_surv - d_surv} dev-only={d_surv - h_surv}")
            assert h_pods == d_pods, (
                f"seed {seed}: assignments/nominations diverged: "
                f"{ {k: (h_pods.get(k), d_pods.get(k)) for k in set(h_pods) | set(d_pods) if h_pods.get(k) != d_pods.get(k)} }")
            assert dev.preemption_device_evals > 0, (
                f"seed {seed}: device dry-run kernel never engaged")

    def test_scalar_resource_victims(self):
        """Victims carrying extended scalar resources intern slots before
        the arrays are built (build_preemption_victims)."""
        from kubernetes_tpu.models.tpu_scheduler import TPUScheduler

        def populate(sched):
            sched.clientset.create_node(
                make_node().name("n0")
                .capacity({"cpu": "4", "memory": "8Gi", "pods": 10,
                           "example.com/gpu": 2}).obj())
            low = make_pod().name("low").req(
                {"cpu": "1", "example.com/gpu": 2}).priority(0).obj()
            sched.clientset.create_pod(low)
            sched.run_until_idle()
            hi = make_pod().name("hi").req(
                {"cpu": "1", "example.com/gpu": 1}).priority(10).obj()
            sched.clientset.create_pod(hi)
            for _ in range(20):
                sched.process_async_api_errors()
                sched.run_until_idle()
            return sched

        host = populate(Scheduler(deterministic_ties=True))
        dev = populate(TPUScheduler())
        assert self._state(host) == self._state(dev)
        assert "low" not in {p.name for p in dev.clientset.pods.values()}
