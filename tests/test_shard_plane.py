"""Scheduler shard plane (kubernetes_tpu/shard/): deterministic partition,
lease CAS + server-side expiry, ring-successor adoption/failback, the
conflict-driven requeue through the backoffQ, and the 2-shard optimistic
bind-conflict storm over a real apiserver (Omega-style shared-state
transactions: the binding subresource 409s the loser, nobody overcommits,
no pod is dropped). Protocol + invariants: docs/SHARDING.md."""

import json
import time

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset
from kubernetes_tpu.shard import (ShardMap, ShardMember, ShardPlane,
                                  lease_name, shard_key, shard_of_key,
                                  shard_of_pod)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _node(name, cpu="8", pods=110):
    return (make_node().name(name)
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": pods})
            .zone(f"z{len(name) % 3}").obj())


def _pod(name, cpu="200m", group=""):
    p = make_pod().name(name).req({"cpu": cpu, "memory": "128Mi"}).obj()
    if group:
        p.pod_group = group
    return p


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# partition
# ---------------------------------------------------------------------------

class TestPartition:
    def test_deterministic_and_in_range(self):
        for count in (1, 2, 3, 7):
            for i in range(50):
                s = shard_of_key(f"uid-{i}", count)
                assert 0 <= s < count
                assert s == shard_of_key(f"uid-{i}", count)  # stable

    def test_spreads_across_shards(self):
        hits = {shard_of_key(f"uid-{i}", 3) for i in range(64)}
        assert hits == {0, 1, 2}

    def test_gangs_pin_whole_to_one_shard(self):
        """PodGroup members key on the group, not the pod uid: gang
        all-or-nothing must never span shards."""
        members = [_pod(f"g-{i}", group="train") for i in range(8)]
        keys = {shard_key(p) for p in members}
        assert len(keys) == 1
        shards = {shard_of_pod(p, 3) for p in members}
        assert len(shards) == 1
        # non-gang pods key on their own uid
        a, b = _pod("solo-a"), _pod("solo-b")
        assert shard_key(a) == a.uid and shard_key(b) == b.uid


# ---------------------------------------------------------------------------
# leases: CAS + expiry (in-process surface; HTTP parity below)
# ---------------------------------------------------------------------------

class TestLeaseCAS:
    def test_acquire_renew_conflict_expire_takeover(self):
        cs = FakeClientset()
        clock = _FakeClock()
        cs.lease_now = clock
        assert cs.upsert_lease("shard-0", "alice", 3.0) is not None
        assert cs.upsert_lease("shard-0", "alice", 3.0) is not None  # renew
        assert cs.upsert_lease("shard-0", "bob", 3.0) is None  # CAS loss
        clock.advance(3.5)  # held lease expires
        got = cs.upsert_lease("shard-0", "bob", 3.0)
        assert got is not None and got["holder"] == "bob"
        assert got["transitions"] == 2  # acquire + takeover
        view = cs.list_leases()
        assert view[0]["holder"] == "bob" and not view[0]["expired"]

    def test_http_surface_parity(self):
        """PUT /api/v1/leases/<name> + GET /api/v1/leases mirror the
        in-process contract: 409 for a held lease, server-side expiry."""
        api = APIServer()
        port = api.serve(0)
        cs = HTTPClientset(f"http://127.0.0.1:{port}")
        assert cs.upsert_lease("shard-0", "alice", 30.0) is not None
        assert cs.upsert_lease("shard-0", "bob", 30.0) is None  # HTTP 409
        leases = cs.list_leases()
        assert [l["name"] for l in leases] == ["shard-0"]
        assert leases[0]["holder"] == "alice"
        assert api.lease_conflicts == 1

    def test_lease_rides_the_wal(self, tmp_path):
        """An upserted lease survives an apiserver restart from the same
        data dir: the holder table recovers, its clock restarted (a live
        holder renews within one period; a dead one expires on schedule)."""
        d = str(tmp_path / "wal")
        api = APIServer(data_dir=d)
        api.upsert_lease("shard-1", "alice", 15.0)
        api2 = APIServer(data_dir=d)  # recovery replays snapshot + WAL
        view = {l["name"]: l for l in api2.list_leases()}
        assert view["shard-1"]["holder"] == "alice"
        assert not view["shard-1"]["expired"]
        assert view["shard-1"]["transitions"] == 1


# ---------------------------------------------------------------------------
# ring-successor ownership (ShardMap.compute_owned)
# ---------------------------------------------------------------------------

class TestRingOwnership:
    def _map(self, cs, clock, index, count=3, duration=3.0):
        m = ShardMap(cs, index, count, lease_duration=duration,
                     identity=f"m{index}", now=clock)
        return m

    def test_all_alive_owns_only_own_slot(self):
        cs, clock = FakeClientset(), _FakeClock()
        cs.lease_now = clock
        for i in range(3):
            cs.upsert_lease(lease_name(i), f"m{i}", 3.0)
        m1 = self._map(cs, clock, 1)
        assert m1.renew_own()
        m1.refresh()
        assert m1.compute_owned(True) == {1}

    def test_expired_slot_adopted_by_ring_successor_only(self):
        cs, clock = FakeClientset(), _FakeClock()
        cs.lease_now = clock
        for i in range(3):
            cs.upsert_lease(lease_name(i), f"m{i}", 3.0)
        clock.advance(2.0)
        # slots 0 and 2 renew; slot 1's holder died
        cs.upsert_lease(lease_name(0), "m0", 3.0)
        cs.upsert_lease(lease_name(2), "m2", 3.0)
        clock.advance(1.5)  # slot 1 now expired (age 3.5 > 3.0)
        m0, m2 = self._map(cs, clock, 0), self._map(cs, clock, 2)
        for m in (m0, m2):
            assert m.renew_own()
            m.refresh()
        # ring successor of 1 is 2 — and ONLY 2
        assert m2.compute_owned(True) == {2, 1}
        assert m0.compute_owned(True) == {0}

    def test_failback_on_peer_return(self):
        cs, clock = FakeClientset(), _FakeClock()
        cs.lease_now = clock
        for i in range(2):
            cs.upsert_lease(lease_name(i), f"m{i}", 3.0)
        clock.advance(4.0)  # both expired; m1 returns, m0 does not
        m1 = self._map(cs, clock, 1, count=2)
        assert m1.renew_own()
        m1.refresh()
        assert m1.compute_owned(True) == {1, 0}
        # dead shard 0 comes back: its renewal makes the slot alive again
        cs.upsert_lease(lease_name(0), "m0-reborn", 3.0)
        m1.refresh()
        assert m1.compute_owned(True) == {1}

    def test_vacant_slot_waits_out_startup_grace(self):
        """A slot with NO lease record may be a peer that hasn't started:
        adoptable only after one full lease period from OUR start. A
        crashed peer that DID start leaves an expired record — adoptable
        immediately on expiry."""
        cs, clock = FakeClientset(), _FakeClock()
        cs.lease_now = clock
        m0 = self._map(cs, clock, 0, count=2)
        assert m0.renew_own()
        m0.refresh()
        assert m0.compute_owned(True) == {0}  # slot 1 vacant, inside grace
        clock.advance(3.5)
        assert m0.renew_own()
        m0.refresh()
        assert m0.compute_owned(True) == {0, 1}  # grace elapsed

    def test_own_cas_loss_owns_nothing(self):
        """A member whose own slot is held by another identity must stop
        admitting entirely (a superseding replacement took the slot)."""
        cs, clock = FakeClientset(), _FakeClock()
        cs.lease_now = clock
        cs.upsert_lease(lease_name(0), "usurper", 30.0)
        m0 = self._map(cs, clock, 0, count=2)
        assert not m0.renew_own()
        m0.refresh()
        assert m0.compute_owned(False) == set()


# ---------------------------------------------------------------------------
# ShardMember: admission, adoption sweep, handback (fake clock, no threads)
# ---------------------------------------------------------------------------

class TestShardMember:
    def _build(self, count=2, duration=3.0):
        clock = _FakeClock()
        cs = FakeClientset()
        cs.lease_now = clock
        sched = Scheduler(clientset=cs, deterministic_ties=True)
        for i in range(8):
            cs.create_node(_node(f"node-{i}"))
        member = ShardMember(sched, 0, count, lease_duration=duration,
                             now=clock)
        return clock, cs, sched, member

    def test_admission_partitions_the_queue(self):
        clock, cs, sched, member = self._build()
        member.tick()
        pods = [_pod(f"p-{i}") for i in range(24)]
        mine = [p for p in pods if shard_of_pod(p, 2) == 0]
        theirs = [p for p in pods if shard_of_pod(p, 2) != 0]
        assert mine and theirs  # both sides populated
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        bound = {p.name for p in cs.pods.values() if p.node_name}
        assert bound == {p.name for p in mine}
        assert sched.queue.pending_counts() == (0, 0, 0)  # theirs never entered

    def test_lease_expiry_adoption_sweeps_pending_pods(self):
        clock, cs, sched, member = self._build()
        cs.upsert_lease(lease_name(1), "peer", 3.0)  # peer starts...
        member.tick()
        pods = [_pod(f"p-{i}") for i in range(24)]
        for p in pods:
            cs.create_pod(p)
        sched.run_until_idle()
        pending = [p for p in cs.pods.values() if not p.node_name]
        assert pending  # shard 1's pods wait for their owner
        clock.advance(4.0)  # ...and dies: lease expires unrenewed
        assert member.tick()
        assert member.owned == {0, 1}
        assert member.adoptions == 1
        sched.run_until_idle()
        assert all(p.node_name for p in cs.pods.values())
        assert sched.metrics.shard_owned_shards.value() == 2.0

    def test_peer_return_hands_range_back(self):
        clock, cs, sched, member = self._build()
        cs.upsert_lease(lease_name(1), "peer", 3.0)
        member.tick()
        clock.advance(4.0)
        member.tick()
        assert member.owned == {0, 1}
        cs.upsert_lease(lease_name(1), "peer-reborn", 3.0)  # failback
        clock.advance(member.renew_interval)
        member.tick()
        assert member.owned == {0}
        assert member.handbacks == 1

    def test_purge_unowned_on_join(self):
        """Pods queued BEFORE the member installed its admission predicate
        (informer replay) leave the queue at construction."""
        clock = _FakeClock()
        cs = FakeClientset()
        cs.lease_now = clock
        sched = Scheduler(clientset=cs, deterministic_ties=True)
        for i in range(4):
            cs.create_node(_node(f"node-{i}"))
        pods = [_pod(f"p-{i}") for i in range(16)]
        for p in pods:
            cs.create_pod(p)  # all 16 enter the queue: no partition yet
        member = ShardMember(sched, 0, 2, lease_duration=3.0, now=clock)
        member.tick()
        sched.run_until_idle()
        bound = {p.name for p in cs.pods.values() if p.node_name}
        assert bound == {p.name for p in pods if shard_of_pod(p, 2) == 0}


# ---------------------------------------------------------------------------
# conflict-driven requeue (deterministic unit seam)
# ---------------------------------------------------------------------------

class _Conflict409(Exception):
    code = 409

    def __init__(self, reason):
        super().__init__(json.dumps({"error": reason}))
        self._body = json.dumps({"error": reason}).encode()

    def read(self):
        return self._body


class _ConflictOnce:
    """Clientset decorator: the FIRST bind raises a 409 (another scheduler
    won the shared state); later binds pass through."""

    def __init__(self, inner, reason="AlreadyBound"):
        self._inner = inner
        self._reason = reason
        self.fired = False

    def bind(self, pod, node_name):
        if not self.fired:
            self.fired = True
            raise _Conflict409(self._reason)
        return self._inner.bind(pod, node_name)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestConflictRequeue:
    def test_bind_409_lands_in_backoffq_and_retries(self):
        cs = FakeClientset()
        sched = Scheduler(clientset=_ConflictOnce(cs),
                          deterministic_ties=True)
        for i in range(4):
            cs.create_node(_node(f"node-{i}"))
        cs.create_pod(_pod("racer"))
        assert sched.schedule_one()  # first attempt: 409 at bind
        assert sched.bind_conflicts == 1
        assert sched.conflict_requeues == 1
        # straight to the backoffQ — never the unschedulable pool, never an
        # error-parked failure
        active, backoff, unsched = sched.queue.pending_counts()
        assert (active + backoff, unsched) == (1, 0)
        assert not sched.error_log
        sched.run_until_idle()  # backoff elapses, retry binds for real
        assert [p.node_name for p in cs.pods.values()] != [""]
        assert sched.scheduled == 1

    def test_conflict_metric_classified_by_reason(self):
        for reason, label in (("AlreadyBound", "already_bound"),
                              ("OutOfCapacity", "capacity")):
            cs = FakeClientset()
            sched = Scheduler(clientset=_ConflictOnce(cs, reason),
                              deterministic_ties=True)
            cs.create_node(_node("node-0"))
            cs.create_pod(_pod("racer"))
            sched.run_until_idle()
            assert sched.metrics.bind_conflict_total.value(label) == 1


# ---------------------------------------------------------------------------
# apiserver Omega commit validation (capacity 409)
# ---------------------------------------------------------------------------

class TestCapacityValidation:
    def test_overcommitting_bind_409s(self):
        api = APIServer()
        port = api.serve(0)
        cs = HTTPClientset(f"http://127.0.0.1:{port}")
        cs.create_node(_node("tight", cpu="1"))  # fits five 200m pods
        pods = [_pod(f"p-{i}") for i in range(6)]
        for p in pods:
            cs.create_pod(p)
        bound = 0
        conflicts = 0
        for p in pods:
            try:
                cs.bind(p, "tight")
                bound += 1
            except Exception as e:  # noqa: BLE001
                assert getattr(e, "code", None) == 409
                conflicts += 1
        assert bound == 5 and conflicts == 1
        assert api.capacity_conflicts == 1
        # releasing one pod frees its share for the loser (server-side
        # store is the truth — local pod copies never mutate over HTTP)
        victim = next(p for p in api.store.pods.values() if p.node_name)
        loser = next(p for p in api.store.pods.values() if not p.node_name)
        cs.delete_pod(victim)
        cs.bind(loser, "tight")
        assert api.store.pods[loser.uid].node_name == "tight"

    def test_same_node_bind_replay_is_idempotent(self):
        """A replayed same-node bind answers 200 (PR 2 contract) and must
        NOT double-count usage — or replays would eat capacity."""
        api = APIServer()
        port = api.serve(0)
        cs = HTTPClientset(f"http://127.0.0.1:{port}")
        cs.create_node(_node("tight", cpu="1", pods=5))
        p = _pod("replayed")
        cs.create_pod(p)
        for _ in range(4):
            cs.bind(p, "tight")  # 1 real + 3 replays
        assert api._usage["tight"]["pods"] == 1


# ---------------------------------------------------------------------------
# 2-shard bind-conflict storm (no partition: the deliberate worst case)
# ---------------------------------------------------------------------------

def test_two_shards_racing_identical_pods_storm():
    """Two full scheduler stacks, NO admission partition, one apiserver:
    both race the same backlog. Invariants under maximal conflict: every
    pod bound exactly once, every 409 became a backoffQ requeue (no pod
    parked as an error), and no node overcommitted."""
    api = APIServer()
    port = api.serve(0)
    url = f"http://127.0.0.1:{port}"
    seed = HTTPClientset(url)
    for i in range(12):
        seed.create_node(_node(f"node-{i}", cpu="8", pods=8))
    n_pods = 60
    built = []

    def factory(cs):
        s = Scheduler(clientset=cs, deterministic_ties=True)
        # Divergent node-rotation origins: two schedulers with IDENTICAL
        # views and tie-breaking pick identical nodes, and a double-bind to
        # the same node is the idempotent replay (200, no conflict). Real
        # multi-scheduler deployments diverge (list order, rotation, timing)
        # — model that honestly so the commits genuinely collide.
        s.next_start_node_index = len(built) * 6
        built.append(s)
        return s

    plane = ShardPlane(url, 2, with_members=False,
                       scheduler_factory=factory)
    try:
        plane.start()
        # Lockstep start: both reflectors must hold the node set BEFORE the
        # backlog lands, or the first-up shard drains it alone and the race
        # never happens.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(len(sh.scheduler.cache.nodes) == 12
                   for sh in plane.shards):
                break
            time.sleep(0.02)
        # waves re-synchronize the race: both shards pop each wave's head
        # at the same time, so 409s keep happening throughout the run
        for wave in range(6):
            for i in range(n_pods // 6):
                seed.create_pod(_pod(f"racer-{wave * 10 + i}", cpu="500m"))
            time.sleep(0.05)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if sum(1 for p in api.store.pods.values() if p.node_name) >= n_pods:
                break
            time.sleep(0.1)
        assert not plane.errors(), plane.errors()
        bound = {p.uid: p.node_name
                 for p in api.store.pods.values() if p.node_name}
        assert len(bound) == n_pods, (
            f"pods dropped under conflict: {len(bound)}/{n_pods}")
        # both schedulers racing one backlog must actually conflict
        total_conflicts = api.bind_conflicts + api.capacity_conflicts
        assert total_conflicts > 0
        assert plane.total("bind_conflicts") == total_conflicts
        # every sync-path 409 requeued through the backoffQ, none errored
        assert plane.total("conflict_requeues") == plane.total("bind_conflicts")
        for sh in plane.shards:
            assert not sh.scheduler.error_log, sh.scheduler.error_log
        # host-oracle overcommit check: per-node committed usage fits
        for node in api.store.nodes.values():
            placed = [p for p in api.store.pods.values()
                      if p.node_name == node.name]
            assert len(placed) <= node.allocatable.allowed_pod_number
            assert (sum(p.resource_request().milli_cpu for p in placed)
                    <= node.allocatable.milli_cpu), node.name
    finally:
        plane.close()
