"""Sustained concurrency stress — the -race-CI analogue (hack/make-rules/
test.sh:87 runs the reference's tests under the race detector; this drives
every concurrent seam of THIS design at once and asserts the invariants the
race detector would protect):

- a creator thread writing pods through the watch-seam transport
  (core/remote.py apiserver thread → cross-thread reflector inbox),
- a churn thread creating/deleting nodes and deleting scheduled pods,
- the thread-mode async API dispatcher executing binds off the loop,
- the device scheduler running sessions with invalidation mid-flight.

Invariants at the end: no scheduler errors, cache ≡ API (CacheDebugger
comparer), every surviving pod bound exactly once to a live-or-deleted node,
in-flight accounting empty, and the run survived without deadlock.
"""

import threading
import time

from kubernetes_tpu.core.config import SchedulerConfiguration
from kubernetes_tpu.core.debugger import CacheDebugger
from kubernetes_tpu.core.remote import RemoteClientset
from kubernetes_tpu.models import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def test_sustained_concurrent_churn_and_scheduling():
    cs = RemoteClientset(rtt=0.0002)
    cfg = SchedulerConfiguration(async_dispatch_threads=True)
    sched = TPUScheduler(clientset=cs, config=cfg)
    for i in range(60):
        cs.create_node(make_node().name(f"n{i}")
                       .capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
                       .zone(f"z{i % 4}").obj())

    N_PODS = 400
    stop = threading.Event()
    errors = []

    def creator():
        try:
            proto = make_pod().name("proto").req(
                {"cpu": "100m", "memory": "64Mi"}).labels({"app": "s"}).obj()
            for i in range(N_PODS):
                if stop.is_set():
                    return
                cs.create_pod(proto.clone_from_template(f"s-{i}"))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def churner():
        try:
            seq = 0
            while not stop.is_set():
                seq += 1
                cs.create_node(make_node().name(f"churn-{seq}")
                               .capacity({"cpu": "8", "pods": 50}).obj())
                if seq > 3:
                    cs.delete_node(f"churn-{seq - 3}")
                # delete an already-scheduled pod now and then
                for p in list(cs.pods.values())[:1]:
                    if p.node_name:
                        cs.delete_pod(p)
                        break
                time.sleep(0.003)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=creator, daemon=True),
               threading.Thread(target=churner, daemon=True)]
    for t in threads:
        t.start()

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        sched.run_until_idle()
        sched.api_dispatcher.flush()
        sched.process_async_api_errors()
        if (not threads[0].is_alive()
                and sched.scheduled >= N_PODS - 40  # churn deletes some
                and not sched.queue.active_q.items()):
            break
        time.sleep(0.002)
    stop.set()
    for t_ in threads:
        t_.join(timeout=5)
    assert not any(t_.is_alive() for t_ in threads), "writer thread hung"
    sched.api_dispatcher.flush()
    sched.run_until_idle()

    assert not errors, errors
    assert not sched.error_log, sched.error_log[:5]
    # every pending pod processed; in-flight accounting empty
    assert not sched.queue._in_flight
    # cache ≡ API store (the race detector's cache-coherence claim)
    dbg = CacheDebugger(sched)
    diffs = dbg.compare()
    assert not diffs, diffs[:5]
    # each surviving bound pod is on exactly one node, and bindings agree
    for p in cs.pods.values():
        if p.node_name:
            assert cs.bindings.get(p.uid) == p.node_name
    cs.close()
