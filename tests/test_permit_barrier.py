"""Permit WAIT / waitingPods (framework.go:2097 WaitOnPermit) with the
GangScheduling barrier plugin, and Storage/Add queueing-hint requeue."""

from kubernetes_tpu.api.storage import WAIT_FOR_FIRST_CONSUMER, PersistentVolumeClaim, StorageClass
from kubernetes_tpu.api.types import NodeSelector, NodeSelectorTerm, PodGroup, Volume
from kubernetes_tpu.api.labels import IN, Requirement
from kubernetes_tpu.api.storage import PersistentVolume
from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class TestPermitBarrier:
    def _sched(self):
        # Gang entity mode OFF: members go through the per-pod Permit barrier
        # (the feature-gated co-scheduling mode, gangscheduling.go).
        cfg = SchedulerConfiguration(
            feature_gates={"GenericWorkload": False, "CompositePodGroup": False},
            profiles=[ProfileConfig(plugins=PluginSet(
                enabled=(("GangScheduling", 0),)))])
        s = Scheduler(config=cfg, deterministic_ties=True)
        for i in range(4):
            s.clientset.create_node(
                make_node().name(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        return s

    def test_members_wait_then_release_together(self):
        s = self._sched()
        s.clientset.create_pod_group(PodGroup(name="gang", min_count=3))
        for i in range(2):
            p = make_pod().name(f"g{i}").req({"cpu": "1"}).obj()
            p.pod_group = "gang"
            s.clientset.create_pod(p)
        s.run_until_idle()
        # Two members parked at the barrier: reserved (assumed) but unbound.
        assert s.scheduled == 0
        assert len(s.waiting_pods) == 2
        assert len(s.cache.assumed_pods) == 2
        p = make_pod().name("g2").req({"cpu": "1"}).obj()
        p.pod_group = "gang"
        s.clientset.create_pod(p)
        s.run_until_idle()
        # Third member satisfied the quorum: all three bind.
        assert s.scheduled == 3
        assert not s.waiting_pods

    def test_barrier_timeout_unwinds(self):
        s = self._sched()
        s.permit_wait_timeout = -1.0  # every wait is immediately expired
        s.clientset.create_pod_group(PodGroup(name="gang", min_count=2))
        p = make_pod().name("g0").req({"cpu": "1"}).obj()
        p.pod_group = "gang"
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 0
        assert not s.waiting_pods          # expired and unwound
        assert not s.cache.assumed_pods    # reservation released


class TestStorageEventRequeue:
    def test_pv_creation_requeues_volume_failure(self):
        s = Scheduler(deterministic_ties=True)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_storage_class(StorageClass(
            name="wffc", volume_binding_mode=WAIT_FOR_FIRST_CONSUMER))
        s.clientset.create_pvc(PersistentVolumeClaim.of("c", "5Gi", storage_class="wffc"))
        pod = make_pod().name("p").req({"cpu": "1"}).obj()
        pod.volumes.append(Volume(name="data", pvc_name="c"))
        s.clientset.create_pod(pod)
        s.run_until_idle()
        assert s.scheduled == 0  # no PV, no provisioner
        # A matching PV appears → Storage/Add hint requeues the pod.
        s.clientset.create_pv(PersistentVolume.of(
            "pv-late", "10Gi", storage_class="wffc",
            node_affinity=NodeSelector(terms=(NodeSelectorTerm(
                match_fields=(Requirement("metadata.name", IN, ("n0",)),)),))))
        s.run_until_idle()
        assert s.scheduled == 1
