"""Streaming paged LIST (`?limit=&continue=`, docs/SCALE.md).

Covers: list_page / continuation-token units; paged-vs-unpaged oracle
equality over HTTP; the randomized pagination fuzz with concurrent writes
between pages (window-contract during churn, exact equality quiesced) on
BOTH leader and follower replicas; the continuation-off-ring 410 path and
reflector RESUME-after-410 (TOO_OLD -> paged re-list, zero server-side
full ADDED replays); and the streaming paged snapshot bootstrap.
"""

import json
import random
import threading
import time
from urllib import request as urlrequest

import pytest

from kubernetes_tpu.core.apiserver import (
    APIServer,
    HTTPClientset,
    _shutdown_conn,
    fetch_paged,
    pod_to_wire,
)
from kubernetes_tpu.core.watchcache import (
    WatchCache,
    mint_continue,
    parse_continue,
)
from kubernetes_tpu.replication import ReplicationTail
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _pod(name, cpu="1m"):
    return make_pod().name(name).req({"cpu": cpu}).obj()


# ---------------------------------------------------------------------------
# units: page walking + continuation tokens
# ---------------------------------------------------------------------------


class TestListPageUnits:
    def _fill(self, wc, n):
        for i in range(1, n + 1):
            w = pod_to_wire(_pod(f"p{i:03d}"))
            event = {"type": "ADDED", "object": w, "rv": i}
            wc.note_event(i, "ADDED", w,
                          data=(json.dumps(event) + "\n").encode(),
                          event=event)

    def test_pages_reassemble_the_sorted_snapshot(self):
        wc = WatchCache("pods")
        self._fill(wc, 23)
        for limit in (1, 4, 7, 23, 50):
            got, last, anchor = [], "", None
            while True:
                objs, next_key, anchor, _rv = wc.list_page(
                    limit, last_key=last, anchor_rv=anchor)
                got.extend(objs)
                if not next_key:
                    break
                last = next_key
            keys = [o["uid"] for o in got]
            assert keys == sorted(keys)
            assert len(got) == 23, limit

    def test_anchor_off_ring_is_410(self):
        wc = WatchCache("pods", capacity=4)
        self._fill(wc, 12)
        # ring holds [9..12]: an anchor of 2 can no longer be replayed
        assert wc.list_page(5, last_key="p002", anchor_rv=2) is None
        assert wc.too_old >= 1
        # a fresh anchor (head) still pages fine
        objs, _nk, anchor, rv = wc.list_page(5)
        assert len(objs) == 5 and anchor == rv == 12

    def test_empty_snapshot_single_empty_page(self):
        wc = WatchCache("pods")
        objs, next_key, anchor, rv = wc.list_page(10)
        assert objs == [] and next_key == "" and anchor == rv == 0

    def test_continue_token_roundtrip_and_garbage(self):
        tok = mint_continue(42, "pod-k", "ep1")
        d = parse_continue(tok)
        assert (d["rv"], d["k"], d["e"]) == (42, "pod-k", "ep1")
        assert parse_continue("!!!not-base64!!!") is None
        assert parse_continue("") is None
        import base64
        assert parse_continue(
            base64.urlsafe_b64encode(b'{"rv": 1}').decode()) is None
        # wrong TYPES inside valid JSON are malformed too (an int() crash
        # in the page handler would tear the connection instead of 410)
        for bad in (b'{"rv": "x", "k": "", "e": "ep"}',
                    b'{"rv": true, "k": "", "e": "ep"}',
                    b'{"rv": 1, "k": 2, "e": "ep"}',
                    b'{"rv": 1, "k": "", "e": null}'):
            assert parse_continue(
                base64.urlsafe_b64encode(bad).decode()) is None

    def test_reinstall_invalidates_sorted_key_cache(self):
        """An install can land on the SAME (rv, size) stamp with different
        keys (epoch-fork snapshot): the sorted-key cache must not serve
        stale keys into a KeyError."""
        wc = WatchCache("pods")
        self._fill(wc, 5)
        wc.list_page(3)   # populate the sorted-key cache at (5, 5)
        other = [pod_to_wire(_pod(f"z{i}")) for i in range(5)]
        wc.reinstall(other, 5)   # same rv, same size, different keys
        objs, _nk, _a, _rv = wc.list_page(10)
        assert {o["uid"] for o in objs} == {w["uid"] for w in other}


# ---------------------------------------------------------------------------
# HTTP: paged == unpaged oracle; 410 paths
# ---------------------------------------------------------------------------


@pytest.fixture()
def api():
    server = APIServer()
    port = server.serve(0)
    try:
        yield server, f"http://127.0.0.1:{port}"
    finally:
        server.shutdown()


class TestPagedListHTTP:
    def test_paged_equals_unpaged_oracle(self, api):
        server, base = api
        server.store.create_node(make_node().name("n0").capacity(
            {"cpu": 64, "memory": "64Gi", "pods": 100}).obj())
        pods = [_pod(f"p{i}") for i in range(37)]
        for p in pods:
            server.store.create_pod(p)
        server._bind_one(pods[0].uid, "n0")
        paged = fetch_paged(base, "pods", limit=5)
        with urlrequest.urlopen(base + "/api/v1/pods", timeout=10) as r:
            oracle = json.loads(r.read())
        key = lambda w: w["uid"]  # noqa: E731
        assert sorted(paged, key=key) == sorted(oracle, key=key)
        assert server.list_pages >= 8          # ceil(37/5) pages
        assert server.list_unpaged == 1        # only the oracle read
        nodes = fetch_paged(base, "nodes", limit=1)
        assert [n["name"] for n in nodes] == ["n0"]

    def test_malformed_continue_is_410(self, api):
        server, base = api
        server.store.create_pod(_pod("p0"))
        import base64
        crafted = base64.urlsafe_b64encode(
            json.dumps({"rv": "x", "k": "", "e": server.epoch})
            .encode()).decode()
        for token in ("garbage", crafted):
            req = urlrequest.Request(
                base + f"/api/v1/pods?limit=5&continue={token}")
            with pytest.raises(Exception) as ei:
                urlrequest.urlopen(req, timeout=10)
            assert getattr(ei.value, "code", None) == 410
        assert server.list_continue_410 >= 2

    def test_expired_continue_is_410_then_restart_completes(self):
        server = APIServer(backlog=8)
        port = server.serve(0)
        base = f"http://127.0.0.1:{port}"
        try:
            for i in range(10):
                server.store.create_pod(_pod(f"p{i:02d}"))
            # First page by hand, keeping its continuation token.
            import http.client as hc
            conn = hc.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("GET", "/api/v1/pods?limit=3")
            resp = conn.getresponse()
            token = ""
            while True:
                line = resp.readline()
                if not line:
                    break
                d = json.loads(line)
                if d.get("type") == "PAGE":
                    token = d.get("continue") or ""
            assert token
            # Overflow the ring (capacity 8) past the anchor.
            for i in range(20):
                server.store.create_pod(_pod(f"q{i:02d}"))
            conn.request("GET", f"/api/v1/pods?limit=3&continue={token}")
            resp = conn.getresponse()
            assert resp.status == 410
            resp.read()
            conn.close()
            assert server.list_continue_410 >= 1
            # fetch_paged restarts from scratch and completes.
            got = fetch_paged(base, "pods", limit=3)
            assert len(got) == 30
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# reflector RESUME-after-410: TOO_OLD -> paged re-list, never a full replay
# ---------------------------------------------------------------------------


class TestReflectorPagedRelist:
    def test_too_old_triggers_paged_relist_not_full_replay(self):
        server = APIServer(backlog=8)
        port = server.serve(0)
        base = f"http://127.0.0.1:{port}"
        try:
            for i in range(4):
                server.store.create_pod(_pod(f"p{i}"))
            cs = HTTPClientset(base)
            try:
                _wait(lambda: (cs._last_rv["pods"] or 0)
                      >= server._seq["pods"], msg="watch live")
                relists0 = cs.relists["pods"]
                for conn in list(cs._responses):
                    _shutdown_conn(conn)
                for i in range(30):
                    server.store.create_pod(_pod(f"q{i}"))
                _wait(lambda: len(cs.pods) == 34, msg="post-overflow sync")
                # the reconnect rode TOO_OLD -> paged re-list...
                assert cs.relists["pods"] > relists0
                assert server.watch_cache["pods"].too_old >= 1
                # ...and the server NEVER served a full ADDED replay: a
                # paged client's re-list is pages, not a materialized
                # stream queue.
                assert server.relisted_watches == 0
                assert server.list_pages > 0
                # the re-attached stream is live: a late create arrives
                server.store.create_pod(_pod("late"))
                _wait(lambda: len(cs.pods) == 35, msg="live after re-list")
            finally:
                cs.close()
        finally:
            server.shutdown()

    def test_server_restart_new_epoch_paged_relist(self):
        server = APIServer()
        port = server.serve(0)
        base = f"http://127.0.0.1:{port}"
        for i in range(6):
            server.store.create_pod(_pod(f"p{i}"))
        cs = None
        server2 = None
        try:
            cs = HTTPClientset(base)
            _wait(lambda: len(cs.pods) == 6, msg="initial sync")
            server.shutdown()
            # A NEW server generation on the same port (fresh epoch, fresh
            # rv counters): the stale-epoch reconnect must ride
            # TOO_OLD -> paged re-list, never resume into foreign history.
            server2 = APIServer()
            server2.serve(port)
            for i in range(3):
                server2.store.create_pod(_pod(f"r{i}"))
            _wait(lambda: set(cs.pods) == set(server2.store.pods),
                  timeout=20, msg="re-list against the new epoch")
            assert server2.relisted_watches == 0
            assert server2.list_pages > 0
        finally:
            if cs is not None:
                cs.close()
            if server2 is not None:
                server2.shutdown()


class TestFreshFilteredAttach:
    def test_selector_transition_in_list_to_attach_gap_upgrades_slims(
            self, api):
        """A shard-filtered paged list slims while selector_refs == 0; a
        selector source lands BEFORE the fresh watch attach. The attach
        must upgrade everything the list slimmed immediately (full
        rv-less MODIFIEDs) — waiting for the next event would leave
        label-less slims in the cache forever on a quiet cluster."""
        from kubernetes_tpu.core.watchcache import shard_of_wire

        server, base = api
        pods = [make_pod().name(f"p{i}").req({"cpu": "1m"})
                .labels({"app": "x"}).obj() for i in range(8)]
        for p in pods:
            server.store.create_pod(p)
        anchor = server._seq["pods"]
        foreign = {p.uid for p in pods
                   if shard_of_wire({"uid": p.uid, "podGroup": ""}, 2) != 0}
        assert foreign  # crc spread: some pods are foreign to shard 0
        # the transition lands in the list->attach gap
        server.store.create_pod(
            make_pod().name("s").req({"cpu": "1m"})
            .spread_constraint(1, "zone").obj())
        import http.client as hc
        conn = hc.HTTPConnection("127.0.0.1", int(base.rsplit(":", 1)[1]),
                                 timeout=10)
        conn.request(
            "GET", f"/api/v1/pods?watch=true&paged=true&fresh=true"
                   f"&shard=0/2&resourceVersion={anchor}"
                   f"&epoch={server.epoch}")
        resp = conn.getresponse()
        try:
            assert resp.status == 200
            upgraded = set()
            saw_resume = saw_spread = False
            deadline = time.monotonic() + 10
            while upgraded != foreign and time.monotonic() < deadline:
                d = json.loads(resp.readline())
                typ = d.get("type")
                if typ == "RESUME":
                    saw_resume = True
                elif typ == "ADDED" and d["object"].get("name") == "s":
                    saw_spread = True   # replayed transition event, full
                    assert not d["object"].get("slim")
                elif typ == "MODIFIED" and d.get("rv") is None:
                    obj = d["object"]
                    assert not obj.get("slim")
                    assert obj.get("labels") == {"app": "x"}
                    upgraded.add(obj["uid"])
            assert saw_resume and saw_spread
            assert upgraded == foreign
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# the pagination fuzz: random page sizes + concurrent writes between pages
# ---------------------------------------------------------------------------


class _ChurnWriter:
    """Background creates/deletes/binds against an in-process server,
    tracking the uid sets the window contract is asserted against."""

    def __init__(self, server, seed=0):
        self.server = server
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.live = {}          # uid -> pod
        self.created = set()    # every uid ever created
        self.deleted = set()
        self._stop = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)

    def _run(self):
        while not self._stop.is_set():
            roll = self.rng.random()
            with self.lock:
                if roll < 0.55 or not self.live:
                    self._seq += 1
                    p = _pod(f"w{self._seq:05d}")
                    self.server.store.create_pod(p)
                    self.live[p.uid] = p
                    self.created.add(p.uid)
                elif roll < 0.8:
                    uid = self.rng.choice(list(self.live))
                    self.server.store.delete_pod(self.live.pop(uid))
                    self.deleted.add(uid)
                else:
                    uid = self.rng.choice(list(self.live))
                    self.server._bind_one(uid, "n0")
            time.sleep(0.001)


def _paged_random(base, kind, rng, server=None):
    """One paged list with a RANDOM page size per request — exercises the
    token chain across uneven pages. Restarts on 410."""
    import http.client as hc
    host = base.split("//", 1)[1]
    conn = hc.HTTPConnection(host, timeout=30)
    try:
        for _ in range(20):
            out, token, expired = [], "", False
            while True:
                limit = rng.randint(1, 40)
                path = f"/api/v1/{kind}?limit={limit}"
                if token:
                    path += f"&continue={token}"
                conn.request("GET", path)
                resp = conn.getresponse()
                if resp.status == 410:
                    resp.read()
                    expired = True
                    break
                assert resp.status == 200
                token = ""
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    d = json.loads(line)
                    if d.get("type") == "PAGE":
                        token = d.get("continue") or ""
                    elif d.get("object") is not None:
                        out.append(d["object"])
                if not token:
                    return out
            if not expired:
                return out
        raise AssertionError("paged list never completed (kept expiring)")
    finally:
        conn.close()


def _run_fuzz(server, read_base, rounds=6, seed=7,
              contract_store=None, converged=lambda: True):
    """The fuzz body, shared by the leader and follower variants: churn
    while paging (window contract per round), then quiesce and assert the
    paged result is IDENTICAL to the unpaged oracle. ``contract_store``
    is the store BEHIND ``read_base`` (the follower's own store when
    paging a replica) — the window contract is asserted against what the
    serving replica actually held."""
    contract_store = contract_store or server.store
    server.store.create_node(make_node().name("n0").capacity(
        {"cpu": 10000, "memory": "1Ti", "pods": 100000}).obj())
    for i in range(60):
        server.store.create_pod(_pod(f"seed{i:03d}"))
    _wait(converged, timeout=20, msg="seed convergence")
    rng = random.Random(seed)
    writer = _ChurnWriter(server, seed=seed).start()
    try:
        for _round in range(rounds):
            with writer.lock:
                before_alive = set(contract_store.pods)
            got = _paged_random(read_base, "pods", rng)
            got_uids = {w["uid"] for w in got}
            with writer.lock:
                after_alive = set(contract_store.pods)
                deleted_during = set(writer.deleted)
                created_ever = set(writer.created)
            # Window contract (docs/SCALE.md): every pod alive on the
            # serving replica through the whole list appears exactly
            # once; pods created/deleted DURING the list may or may not;
            # nothing else can.
            stable = before_alive & after_alive
            missing = stable - got_uids - deleted_during
            assert not missing, f"stable pods missing: {missing}"
            phantom = got_uids - before_alive - created_ever
            assert not phantom, f"phantom pods: {phantom}"
            assert len(got_uids) == len(got), "duplicate uid in one list"
    finally:
        writer.stop()
    # Quiesced: paged (random page sizes) == unpaged oracle, exactly —
    # including bind state.
    _wait(converged, timeout=20, msg="replica convergence")
    with urlrequest.urlopen(read_base + "/api/v1/pods",
                            timeout=30) as r:
        oracle = {w["uid"]: w.get("nodeName", "")
                  for w in json.loads(r.read())}
    for _ in range(3):
        got = _paged_random(read_base, "pods", rng)
        assert {w["uid"]: w.get("nodeName", "") for w in got} == oracle
    return writer


class TestPaginationFuzz:
    def test_fuzz_on_leader(self, api):
        server, base = api
        _run_fuzz(server, base)

    def test_fuzz_on_follower_replica(self):
        leader = APIServer()
        leader.serve(0)
        follower = APIServer()
        tail = ReplicationTail(follower, leader.advertise_url, rank=1,
                               lease_duration=5.0, page_limit=16)
        try:
            tail.bootstrap()
            fport = follower.serve(0)
            tail.start()
            _run_fuzz(
                leader, f"http://127.0.0.1:{fport}",
                contract_store=follower.store,
                converged=lambda: (
                    follower._seq == leader._seq
                    and len(follower.store.pods) == len(leader.store.pods)))
            # the cold bootstrap streamed PAGES, not one body
            assert leader.snapshot_bootstrap_pages >= 1
            assert tail.bootstraps == 1
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()


# ---------------------------------------------------------------------------
# streaming paged snapshot bootstrap
# ---------------------------------------------------------------------------


class TestPagedSnapshotBootstrap:
    def test_cold_follower_pages_the_bootstrap(self):
        leader = APIServer()
        leader.serve(0)
        leader.store.create_node(make_node().name("n0").capacity(
            {"cpu": 64, "memory": "64Gi", "pods": 500}).obj())
        pods = [_pod(f"p{i:03d}") for i in range(90)]
        for p in pods:
            leader.store.create_pod(p)
        leader._bind_one(pods[0].uid, "n0")
        leader.upsert_lease("shard-0", "holder-a", 5.0)
        follower = APIServer()
        tail = ReplicationTail(follower, leader.advertise_url, rank=1,
                               lease_duration=5.0, page_limit=7)
        try:
            tail.bootstrap()
            assert len(follower.store.pods) == 90
            assert len(follower.store.nodes) == 1
            assert follower.store.bindings.get(pods[0].uid) == "n0"
            assert any(rec["name"] == "shard-0"
                       for rec in follower.list_leases())
            assert follower.epoch == leader.epoch
            assert follower._repl_seq == leader._repl_seq
            # ceil(90/7) pod pages + 1 node page at least
            assert leader.snapshot_bootstrap_pages >= 14
        finally:
            tail.stop()
            follower.shutdown()
            leader.shutdown()

    def test_torn_snapshot_stream_is_never_installed(self):
        """A stream without SNAP_END (leader died mid-bootstrap) must
        raise, not install a partial store."""
        leader = APIServer()
        port = leader.serve(0)
        for i in range(10):
            leader.store.create_pod(_pod(f"p{i}"))
        follower = APIServer()
        tail = ReplicationTail(follower, f"http://127.0.0.1:{port}",
                               rank=1, lease_duration=5.0, page_limit=3)

        class _TornResp:
            """Wrap the response: deliver a bounded byte budget, then EOF
            early — tears mid-stream under EITHER codec (the binary
            reader consumes via read(), the JSON plane via readline())."""

            def __init__(self, resp):
                self._resp = resp
                self._budget = 160

            @property
            def status(self):
                return self._resp.status

            def read(self, *a):
                if self._budget <= 0:
                    return b""   # torn: connection died mid-stream
                data = self._resp.read(*a)
                self._budget -= len(data)
                return data

            def readline(self):
                if self._budget <= 0:
                    return b""
                line = self._resp.readline()
                self._budget -= len(line)
                return line

        import http.client as hc
        orig_getresponse = hc.HTTPConnection.getresponse

        def torn_getresponse(conn):
            return _TornResp(orig_getresponse(conn))

        hc.HTTPConnection.getresponse = torn_getresponse
        try:
            with pytest.raises(Exception, match="torn|SNAP_END"):
                tail._fetch_snapshot_stream()
        finally:
            hc.HTTPConnection.getresponse = orig_getresponse
            follower.shutdown()
            leader.shutdown()
        assert len(follower.store.pods) == 0


# ---------------------------------------------------------------------------
# incremental sorted-key index (PR-16 satellite)
# ---------------------------------------------------------------------------


class TestIncrementalSortedKeyIndex:
    def _fill(self, wc, n, prefix="p"):
        for i in range(1, n + 1):
            w = pod_to_wire(_pod(f"{prefix}{i:03d}"))
            event = {"type": "ADDED", "object": w, "rv": i}
            wc.note_event(i, "ADDED", w,
                          data=(json.dumps(event) + "\n").encode(),
                          event=event)

    def _note(self, wc, rv, typ, w):
        event = {"type": typ, "object": w, "rv": rv}
        wc.note_event(rv, typ, w,
                      data=(json.dumps(event) + "\n").encode(),
                      event=event)

    def _walk(self, wc, limit):
        out, last = [], ""
        while True:
            objs, next_key, _a, _rv = wc.list_page(limit, last_key=last)
            out.extend(objs)
            if not next_key:
                return out
            last = next_key

    def test_churn_maintains_index_without_resort(self):
        """The first page pays ONE lazy sort; every add/delete after that
        maintains the index incrementally (insort / bisect-remove), so a
        churning fleet pages forever on `key_resorts == 1` and every walk
        still reassembles the exact sorted snapshot."""
        wc = WatchCache("pods")
        self._fill(wc, 40)
        wc.list_page(7)
        assert wc.key_resorts == 1
        rv = 40
        for i in range(60):
            rv += 1
            if i % 3 == 2:
                # delete a currently-live pod
                key = sorted(wc._objects)[i % len(wc._objects)]
                self._note(wc, rv, "DELETED", dict(wc._objects[key]))
            else:
                self._note(wc, rv, "ADDED",
                           pod_to_wire(_pod(f"churn{i:03d}")))
            got = self._walk(wc, 9)
            assert [o["uid"] for o in got] == sorted(wc._objects)
        assert wc.key_resorts == 1  # never re-sorted under churn

    def test_reinstall_rebuilds_lazily_exactly_once(self):
        wc = WatchCache("pods")
        self._fill(wc, 10)
        wc.list_page(4)
        assert wc.key_resorts == 1
        wc.reinstall([pod_to_wire(_pod(f"z{i}")) for i in range(10)], 10)
        self._walk(wc, 3)     # first page after install rebuilds...
        self._walk(wc, 3)     # ...and later walks ride the same index
        assert wc.key_resorts == 2

    def test_http_churn_pages_stay_incremental(self, api):
        """Over HTTP: page a churning cluster repeatedly; the server's pod
        cache pays exactly one sort, paged==unpaged once quiesced, and the
        `apiserver_watch_cache_key_resorts_total` series carries it."""
        server, base = api
        for i in range(150):
            server.store.create_pod(_pod(f"seed{i:03d}"))
        assert fetch_paged(base, "pods", limit=16)
        assert server.watch_cache["pods"].key_resorts == 1
        for i in range(40):
            server.store.create_pod(_pod(f"late{i:03d}"))
            if i % 2:
                victim = next(iter(server.store.pods.values()))
                server.store.delete_pod(victim)
            got = fetch_paged(base, "pods", limit=11)
            assert len({w["uid"] for w in got}) == len(got)
        assert server.watch_cache["pods"].key_resorts == 1
        with urlrequest.urlopen(base + "/api/v1/pods", timeout=30) as r:
            oracle = {w["uid"] for w in json.loads(r.read())}
        assert {w["uid"]
                for w in fetch_paged(base, "pods", limit=13)} == oracle
        with urlrequest.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        line = [ln for ln in text.splitlines()
                if ln.startswith("apiserver_watch_cache_key_resorts_total ")]
        assert line and float(line[0].split()[1]) >= 1
