"""Score-hint fast path (models/score_hints.py): churn-equivalence fuzz.

The hint cache binds identical replicas host-side with ZERO device
dispatches (ISSUE 12; KEP-5598 OpportunisticBatch, cross-cycle). The repo's
core invariant applies to it unchanged: hint-path placements must be
BIT-IDENTICAL to the always-dispatch oracle, under randomized journal event
streams interleaved with hint binds — node taint/allocatable churn, bound-
pod deletes, namespace sweeps, unschedulable floods, the 0→1 affinity-pod
transition (hints disabled cluster-wide, mirroring the watch plane's
selector gate), bind-409 single-node invalidation, and shard adoption
mid-stream. The hit counter is asserted > 0 throughout: equivalence with
the hint path demonstrably ENGAGED, not silently fallen through.

Also here: the requeue_conflict enqueued_at regression (conflict retries
must not restart the scheduler_e2e_scheduling_duration_seconds clock).
"""

import random

import pytest

from kubernetes_tpu.core.framework import Status
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _node(name, cpu=8, taint=None, pods=110):
    b = (make_node().name(name)
         .capacity({"cpu": cpu, "memory": "32Gi", "pods": pods})
         .zone(f"zone-{len(name) % 3}"))
    if taint:
        b = b.taint(*taint)
    return b.obj()


def _pod(name, ns="default", cpu="200m", labels=None, anti=None):
    b = make_pod().name(name).namespace(ns).req({"cpu": cpu,
                                                 "memory": "128Mi"})
    if labels:
        b = b.labels(dict(labels))
    if anti:
        b = b.pod_affinity("kubernetes.io/hostname", anti, anti=True)
    return b.obj()


def _pair(n_nodes=24, max_batch=64, oracle_hints=False):
    """(always-dispatch oracle, hint-enabled device scheduler) over
    identical clusters. The oracle is a TPUScheduler with the hint cache
    disabled — the exact code path every pod takes today. mesh=None keeps
    this suite on the single-device plane; mesh sessions install hints
    from the sharded carry too (one device→host gather — ROADMAP 12d,
    TestMeshAndLapWalk)."""
    oracle = TPUScheduler(max_batch=max_batch, mesh=None)
    oracle._hints.enabled = oracle_hints
    dev = TPUScheduler(max_batch=max_batch, mesh=None)
    assert dev._hints.enabled
    for s in (oracle, dev):
        for i in range(n_nodes):
            s.clientset.create_node(_node(f"node-{i}"))
    return oracle, dev


def _assignments(s):
    return {f"{p.namespace}/{p.name}": p.node_name
            for p in s.clientset.pods.values()}


def _both(a, b, fn):
    fn(a)
    fn(b)
    a.run_until_idle()
    b.run_until_idle()


def _assert_identical(oracle, dev, ctx=""):
    ao, ad = _assignments(oracle), _assignments(dev)
    diffs = {k: (ao[k], ad.get(k)) for k in ao if ao[k] != ad.get(k)}
    assert not diffs, f"hint/oracle divergence {ctx}: {diffs}"


class TestHintFastPath:
    def test_identical_replicas_bind_without_dispatch(self):
        """The headline shape: after one seeding session, every identical
        replica binds via the hint — hit counter moves, dispatch counter
        does not, placements match the always-dispatch oracle."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(8)])
        assert dev._hints.entry is not None
        b0 = dev.device_batches
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"rep-{i}")) for i in range(40)])
        _assert_identical(oracle, dev)
        assert dev.hint_hits >= 40
        assert dev.device_batches == b0, "hint path still dispatched"
        assert dev.metrics.hint_cache_hits.value("exact") >= 40
        assert dev.metrics.hint_validation_duration.count() >= 40

    def test_neutral_signature_shares_hint_across_namespaces(self):
        """Replicas differing only in namespace/labels ride ONE hint (the
        namespace-erased neutral signature, PR 3's collapse)."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}", ns="ns-a")) for i in range(6)])
        b0 = dev.device_batches
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"rep-{i}", ns=f"ns-{i % 5}",
                 labels={"app": f"dep-{i % 5}"})) for i in range(30)])
        _assert_identical(oracle, dev)
        assert dev.device_batches == b0
        assert dev.metrics.hint_cache_hits.value("neutral") > 0

    def test_infeasible_replica_falls_through_with_exact_diagnosis(self):
        """Capacity exhaustion mid-run: the hint walk reports -1 and the
        pod falls through to the normal path for the oracle's diagnosis;
        outcomes stay identical."""
        oracle, dev = _pair(n_nodes=3)
        # 3 nodes x 8 cpu; 2000m pods -> 12 fit, the rest are unschedulable.
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}", cpu="2000m")) for i in range(4)])
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"rep-{i}", cpu="2000m")) for i in range(12)])
        _assert_identical(oracle, dev)
        assert dev.hint_hits > 0
        assert dev.metrics.hint_cache_misses.value("infeasible") > 0
        # the unschedulable tail parked identically on both sides
        assert (len(oracle.queue.unschedulable)
                == len(dev.queue.unschedulable) > 0)


class TestHintFreshness:
    """The event-kind → hint-survival matrix (docs/PERF.md)."""

    def test_node_update_dirties_one_row_hint_survives(self):
        """A NoSchedule taint toggling on one node is an EV_NODE_UPDATE:
        the hint re-validates that ROW and keeps serving — no full
        invalidation, placements still oracle-identical."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(8)])
        assert dev._hints.entry is not None
        for rnd in range(3):
            def taint_step(s, rnd=rnd):
                s.clientset.update_node(_node(
                    f"node-{rnd}", taint=("maint", "", "NoSchedule")))
                for i in range(10):
                    s.clientset.create_pod(_pod(f"r{rnd}-{i}"))
            _both(oracle, dev, taint_step)
            def lift_step(s, rnd=rnd):
                s.clientset.update_node(_node(f"node-{rnd}"))
                for i in range(4):
                    s.clientset.create_pod(_pod(f"l{rnd}-{i}"))
            _both(oracle, dev, lift_step)
        _assert_identical(oracle, dev)
        assert dev._hints.entry is not None, "node_update killed the hint"
        assert dev.hint_hits >= 40

    def test_bound_pod_delete_reencodes_row(self):
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}", cpu="1500m")) for i in range(10)])
        for rnd in range(3):
            def step(s, rnd=rnd):
                vs = sorted((p for p in s.clientset.pods.values()
                             if p.node_name), key=lambda p: p.name)
                s.clientset.delete_pod(vs[rnd])
                for i in range(6):
                    s.clientset.create_pod(_pod(f"r{rnd}-{i}", cpu="1500m"))
            _both(oracle, dev, step)
        _assert_identical(oracle, dev)
        assert dev.hint_hits > 0
        assert dev._hints.entry is not None

    def test_pns_taint_kills_hint(self):
        """A PreferNoSchedule taint appearing means the compiled no-PNS
        score path no longer matches the oracle: the hint must die and the
        normal path take over (still oracle-identical)."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        assert dev._hints.entry is not None
        def step(s):
            s.clientset.update_node(_node(
                "node-1", taint=("soft", "", "PreferNoSchedule")))
            for i in range(10):
                s.clientset.create_pod(_pod(f"r-{i}"))
        _both(oracle, dev, step)
        _assert_identical(oracle, dev)
        assert dev.metrics.hint_cache_invalidations.value("pns_taint") == 1

    def test_structural_event_kills_hint(self):
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        assert dev._hints.entry is not None
        def step(s):
            s.clientset.create_node(_node("node-new"))
            for i in range(10):
                s.clientset.create_pod(_pod(f"r-{i}"))
        _both(oracle, dev, step)
        _assert_identical(oracle, dev)
        assert dev.metrics.hint_cache_invalidations.value("structural") == 1

    def test_affinity_transition_disables_hints_cluster_wide(self):
        """0→1 affinity-pod transition: once ANY affinity-term pod is
        placed, labels/namespaces are scheduling-relevant — hints are
        disabled cluster-wide (the watch plane's selector-gate shape) and
        no new hint installs until the count drops back to zero."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}", labels={"app": "web"})) for i in range(6)])
        assert dev._hints.entry is not None
        def step(s):
            s.clientset.create_pod(_pod("anti-0", labels={"app": "web"},
                                        anti={"app": "web"}))
            for i in range(10):
                s.clientset.create_pod(_pod(f"r-{i}", labels={"app": "web"}))
        _both(oracle, dev, step)
        _assert_identical(oracle, dev)
        assert dev._hints.entry is None
        assert dev.cache.affinity_pod_refs > 0
        # sessions while refs > 0 must NOT reinstall
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"r2-{i}", labels={"app": "web"})) for i in range(6)])
        assert dev._hints.entry is None
        _assert_identical(oracle, dev)

    def test_journal_gap_kills_hint(self):
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        assert dev._hints.entry is not None
        # Overflow the journal window with queue-only records, then pop a
        # replica: since() returns None -> journal_gap invalidation.
        for _ in range(dev.journal.cap + 8):
            dev._record_event("queue", "x")
            oracle._record_event("queue", "x")
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"r-{i}")) for i in range(8)])
        _assert_identical(oracle, dev)
        assert dev.metrics.hint_cache_invalidations.value("journal_gap") == 1

    def test_foreign_attempt_kills_hint(self):
        """A pod the walker did not bind (different signature -> device
        session) moves state the journal does not record: the attempts
        fence must invalidate before the next hint serve."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        entry0 = dev._hints.entry
        assert entry0 is not None
        def step(s):
            s.clientset.create_pod(_pod("big-0", cpu="900m"))
        _both(oracle, dev, step)
        # the big pod's own session replaced (or will replace) the entry;
        # serving the stale one must have been fenced, not reused
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"r-{i}")) for i in range(8)])
        _assert_identical(oracle, dev)


class TestBindConflict409:
    def test_conflict_invalidates_single_node_only(self):
        """A bind-409 blocks the hinted NODE; the hint survives, the loser
        re-enters through requeue_conflict, and the next identical pod
        picks a different node host-side."""
        _oracle, dev = _pair(n_nodes=8)
        for i in range(6):
            dev.clientset.create_pod(_pod(f"seed-{i}"))
        dev.run_until_idle()
        entry = dev._hints.entry
        assert entry is not None
        fw = next(iter(dev.profiles.values()))
        binder = fw.bind_plugins[0]
        real_bind = binder.bind
        fails = {"n": 0}
        def flaky_bind(state, pod, node_name, _rb=real_bind):
            if fails["n"] == 0:
                fails["n"] += 1
                st = Status.error(f"bind conflict: OutOfCapacity on "
                                  f"{node_name}")
                st.conflict = True
                flaky_bind.node = node_name
                return st
            return _rb(state, pod, node_name)
        binder.bind = flaky_bind
        try:
            for i in range(6):
                dev.clientset.create_pod(_pod(f"rep-{i}"))
            dev.run_until_idle()
            for _ in range(10):
                dev.process_async_api_errors()
                dev.run_until_idle()
        finally:
            binder.bind = real_bind
            dev.run_until_idle()
        # single-node invalidation: the entry survived, the conflicted row
        # is blocked, and later replicas still rode the hint
        assert dev._hints.entry is entry
        row = entry.row_of[flaky_bind.node]
        assert entry.blocked[row]
        assert not entry.ok[row]
        assert dev.metrics.hint_cache_invalidations.value(
            "bind_conflict") == 1
        assert dev.bind_conflicts == 1
        # every replica is bound exactly once despite the conflict
        bound = [p for p in dev.clientset.pods.values()
                 if p.name.startswith("rep-") and p.node_name]
        assert len(bound) == 6

    def test_async_conflict_takes_back_the_hint_hit(self):
        """Thread-mode binds commit optimistically: a LATER async 409 must
        take the counted hit back (hint_hits would otherwise exceed pods
        actually bound, HintHitRate > 1.0 on contended runs) — while a
        CONFIRMED bind settles the tag, so a later unrelated conflict for
        the same object never erases a real hit."""
        _oracle, dev = _pair(n_nodes=8)
        for i in range(6):
            dev.clientset.create_pod(_pod(f"seed-{i}"))
        dev.run_until_idle()
        p = _pod("rep-0")
        dev.clientset.create_pod(p)
        dev.run_until_idle()
        assert dev.hint_hits == 1
        node = p.node_name
        # the inline FakeClientset confirm settled the optimistic tag
        assert "_hint_bound" not in p.__dict__, "confirm left the tag live"

        class _E(Exception):
            code = 409

            def read(self):
                return b'{"error": "AlreadyBound"}'

        # a LATER conflict in this object's next life must NOT take back
        # the settled hit
        dev.handle.on_async_bind_error(p, _E())
        assert dev.hint_hits == 1, "settled hit was erased"
        # an UNSETTLED optimistic hit (409 arrives before any confirm —
        # the real async-conflict interleaving) is taken back
        p.__dict__["_hint_bound"] = True
        dev.handle.on_async_bind_error(p, _E())
        assert dev.hint_hits == 0, "async 409 left the optimistic hit"
        entry = dev._hints.entry
        assert entry is not None and entry.blocked[entry.row_of[node]]

    def test_permit_wait_park_is_not_a_hint_hit(self):
        """_commit returns True for a Permit-WAIT park, but the pod is
        assumed-unbound: the walker applies the placement (it occupies the
        node) WITHOUT counting a hit — hits count binds only."""
        from kubernetes_tpu.core.framework import OK, Status, WAIT
        from kubernetes_tpu.core.registry import build_framework

        class ParkNamed:
            name = "ParkNamed"

            def permit(self, state, pod, node_name):
                if pod.name == "waitme":
                    return Status(WAIT, ("parked",), self.name)
                return OK

        def factory(h):
            fw = build_framework(h)
            fw.permit_plugins.append(ParkNamed())
            return {"default-scheduler": fw}

        dev = TPUScheduler(max_batch=64, mesh=None,
                           profile_factory=factory)
        for i in range(8):
            dev.clientset.create_node(_node(f"node-{i}"))
        for i in range(6):
            dev.clientset.create_pod(_pod(f"seed-{i}"))
        dev.run_until_idle()
        assert dev._hints.entry is not None
        hits0 = dev.hint_hits
        dev.clientset.create_pod(_pod("waitme"))
        dev.run_until_idle()
        assert len(dev.waiting_pods) == 1
        assert dev.hint_hits == hits0, "a parked (unbound) pod was a hit"
        # the walker applied the park: allowing it binds on the hinted node
        uid = next(iter(dev.waiting_pods))
        assert dev.allow_waiting_pod(uid)
        bound = next(p for p in dev.clientset.pods.values()
                     if p.name == "waitme")
        assert bound.node_name

    def test_disabling_hints_stops_a_warm_entry(self):
        """The A/B seam (`_hints.enabled = False` after a wave installed
        an entry) must actually force the dispatch-only baseline."""
        _oracle, dev = _pair()
        for i in range(6):
            dev.clientset.create_pod(_pod(f"seed-{i}"))
        dev.run_until_idle()
        assert dev._hints.entry is not None
        dev._hints.enabled = False
        b0 = dev.device_batches
        for i in range(8):
            dev.clientset.create_pod(_pod(f"rep-{i}"))
        dev.run_until_idle()
        assert dev.hint_hits == 0, "disabled hint cache still served"
        assert dev._hints.entry is None
        assert dev.device_batches > b0, "replicas did not dispatch"

    def test_pod_event_on_blocked_row_unblocks_it(self):
        _oracle, dev = _pair(n_nodes=8)
        for i in range(6):
            dev.clientset.create_pod(_pod(f"seed-{i}"))
        dev.run_until_idle()
        entry = dev._hints.entry
        assert entry is not None
        node = entry.node_names[0]
        dev._note_bind_conflict("OutOfCapacity", _pod("x"), node)
        assert entry.blocked[entry.row_of[node]]
        # a foreign bind landing on that node re-encodes it from truth
        foreign = _pod("foreign-0")
        foreign.node_name = node
        dev.clientset.create_pod(foreign)
        dev.run_until_idle()
        for i in range(4):
            dev.clientset.create_pod(_pod(f"after-{i}"))
        dev.run_until_idle()
        if dev._hints.entry is entry:  # survived the replay
            assert not entry.blocked[entry.row_of[node]]


class TestMeshAndLapWalk:
    """ROADMAP 12a/12d: the lap-batched walk (one cumsum serves a lap of
    replicas) and mesh-session hint installs (the HintEntry fetches the
    per-node aggregates/score vector from the SHARDED carry via one
    device→host gather at clean session end)."""

    def test_mesh_session_installs_hint_from_sharded_carry(self):
        from kubernetes_tpu.parallel import make_mesh
        oracle = TPUScheduler(max_batch=64, mesh=None)
        oracle._hints.enabled = False
        dev = TPUScheduler(max_batch=64, mesh=make_mesh(n_cells=1))
        assert dev.mesh is not None and dev._hints.enabled
        for s in (oracle, dev):
            for i in range(24):
                s.clientset.create_node(_node(f"node-{i}"))
        proto = _pod("proto")
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"a-{i}")) for i in range(8)])
        # clean mesh session end → hint installed from the sharded carry
        assert dev._hints.entry is not None, (
            "mesh session did not install a score hint")
        batches0 = dev.device_batches
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"b-{i}")) for i in range(12)])
        _assert_identical(oracle, dev, "(mesh hint binds)")
        assert dev.hint_hits >= 12, dev.hint_hits
        assert dev.device_batches == batches0, (
            "hint-eligible replicas dispatched to the mesh anyway")

    def test_lap_batched_walk_is_bit_identical_and_engaged(self):
        """With adaptive-sampling truncation live (to_find << feasible),
        the walk precomputes a LAP of placements per cumsum — assert it
        demonstrably engages (lap_walks < hits) and stays bit-identical
        to the always-dispatch oracle."""
        oracle = TPUScheduler(max_batch=32, mesh=None)
        oracle._hints.enabled = False
        dev = TPUScheduler(max_batch=32, mesh=None)
        for s in (oracle, dev):
            s.percentage_of_nodes_to_score = 10  # to_find=20 at 200 nodes
            for i in range(200):
                s.clientset.create_node(_node(f"node-{i}"))
        proto = _pod("proto", cpu="100m")
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"a-{i}")) for i in range(8)])
        entry = dev._hints.entry
        assert entry is not None and entry.lap_enabled
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"b-{i}")) for i in range(60)])
        _assert_identical(oracle, dev, "(lap walk)")
        assert dev.hint_hits >= 60
        e = dev._hints.entry
        assert e is not None and e.lap_walks >= 1
        # batching engaged: far fewer full walks than pods served
        assert e.lap_walks * 2 <= dev.hint_hits, (
            e.lap_walks, dev.hint_hits)

    def test_lap_disabled_env_pins_per_pod_walk(self, monkeypatch):
        monkeypatch.setenv("TPU_SCHED_HINT_LAP", "0")
        oracle = TPUScheduler(max_batch=32, mesh=None)
        oracle._hints.enabled = False
        dev = TPUScheduler(max_batch=32, mesh=None)
        for s in (oracle, dev):
            s.percentage_of_nodes_to_score = 10
            for i in range(200):
                s.clientset.create_node(_node(f"node-{i}"))
        proto = _pod("proto", cpu="100m")
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"a-{i}")) for i in range(8)])
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            proto.clone_from_template(f"b-{i}")) for i in range(20)])
        _assert_identical(oracle, dev, "(per-pod walk)")
        e = dev._hints.entry
        assert e is not None and not e.lap_enabled and e.lap_walks == 0


class TestRequeueConflictEnqueuedAt:
    def test_async_conflict_requeue_preserves_enqueued_at(self):
        """Regression (ISSUE 12 satellite): the async bind-conflict path
        rebuilds a QueuedPodInfo from the bare Pod — it must carry the
        ORIGINAL queue-admission instant so the e2e histogram covers the
        whole conflict retry, not just the post-conflict leg."""
        s = Scheduler()
        s.clientset.create_node(_node("n-0"))
        p = _pod("victim")
        s.queue.add(p)
        qpi = s.queue.pop()
        orig = qpi.enqueued_at
        assert orig is not None
        s.queue.done(p.uid)
        # simulate the winning scheduler's raced bind: 409 on our async bind
        p.node_name = "n-0"
        s.cache.assume_pod(p, qpi.pod_info)

        class _E(Exception):
            code = 409

            def read(self):
                return b'{"error": "AlreadyBound"}'

        s.handle.on_async_bind_error(p, _E())
        requeued = (s.queue.backoff_q.get(p.uid)
                    or s.queue.active_q.get(p.uid))
        assert requeued is not None
        assert requeued.enqueued_at == orig, (
            "conflict requeue restarted the e2e clock")

    def test_sync_conflict_requeue_preserves_enqueued_at(self):
        """The sync path passes the original qpi through requeue_conflict —
        pin that it keeps enqueued_at while resetting the backoff stamp."""
        s = Scheduler()
        p = _pod("victim")
        s.queue.add(p)
        qpi = s.queue.pop()
        orig = qpi.enqueued_at
        s.queue.done(p.uid)
        s.queue.requeue_conflict(qpi)
        got = s.queue.backoff_q.get(p.uid) or s.queue.active_q.get(p.uid)
        assert got is qpi and got.enqueued_at == orig


class TestShardAdoptionMidStream:
    def test_adoption_admits_pods_into_live_hint_run(self):
        """Shard adoption mid-stream: pods initially outside this
        scheduler's admission predicate join the queue later (the
        sweep_pending shape). They must ride the live hint and land
        exactly where the oracle puts them."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-{i}")) for i in range(6)])
        assert dev._hints.entry is not None
        # attach an admission predicate rejecting the adopted range
        rejected = set()
        def admit(pod):
            return pod.name not in rejected
        for s in (oracle, dev):
            s.pod_admission = admit
        rejected.update(f"adopt-{i}" for i in range(10))
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"adopt-{i}")) for i in range(10)])
        assert not any(p.node_name for p in dev.clientset.pods.values()
                       if p.name.startswith("adopt-"))
        # ownership grows: admit and sweep (queue-only — the hint survives)
        rejected.clear()
        def sweep(s):
            for p in s.clientset.pods.values():
                if (p.name.startswith("adopt-") and not p.node_name
                        and not s.queue.has_entity(p.uid)):
                    s.queue.add(p)
        _both(oracle, dev, sweep)
        _assert_identical(oracle, dev)
        assert all(p.node_name for p in dev.clientset.pods.values()
                   if p.name.startswith("adopt-"))
        assert dev.hint_hits > 0


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_churn_equivalence_fuzz(seed):
    """Randomized journal event streams interleaved with hint-path binds:
    placements bit-identical to the always-dispatch oracle, hint path
    demonstrably engaged (hit counter > 0)."""
    rng = random.Random(seed)
    oracle, dev = _pair()
    _both(oracle, dev, lambda s: [s.clientset.create_pod(
        _pod(f"seed-{i}")) for i in range(8)])
    tainted = {}
    wave = 0
    for rnd in range(12):
        action = rng.choice(
            ["replicas", "replicas", "replicas", "taint", "lift",
             "drift", "delete_bound", "namespace", "flood", "ns_sweep"])
        if action == "replicas":
            n = rng.randrange(1, 12)
            wave += 1
            _both(oracle, dev, lambda s, n=n, w=wave: [
                s.clientset.create_pod(_pod(f"w{w}-{i}"))
                for i in range(n)])
        elif action == "taint":
            i = rng.randrange(24)
            tainted[i] = ("maint", "", "NoSchedule")
            _both(oracle, dev, lambda s, i=i: s.clientset.update_node(
                _node(f"node-{i}", taint=tainted[i])))
        elif action == "lift":
            if tainted:
                i = rng.choice(list(tainted))
                del tainted[i]
                _both(oracle, dev, lambda s, i=i: s.clientset.update_node(
                    _node(f"node-{i}")))
        elif action == "drift":
            i = rng.randrange(24)
            cpu = rng.choice([6, 8, 10])
            _both(oracle, dev, lambda s, i=i, cpu=cpu:
                  s.clientset.update_node(
                      _node(f"node-{i}", cpu=cpu,
                            taint=tainted.get(i))))
        elif action == "delete_bound":
            def step(s):
                vs = sorted((p for p in s.clientset.pods.values()
                             if p.node_name), key=lambda p: p.name)
                if vs:
                    s.clientset.delete_pod(vs[0])
            _both(oracle, dev, step)
        elif action == "namespace":
            from kubernetes_tpu.api.types import Namespace
            _both(oracle, dev, lambda s, r=rnd: s.clientset.create_namespace(
                Namespace(name=f"fuzz-ns-{r}", labels={"round": str(r)})))
        elif action == "flood":
            wave += 1
            _both(oracle, dev, lambda s, w=wave: [
                s.clientset.create_pod(_pod(f"big{w}-{i}", cpu="32000m"))
                for i in range(2)])
        elif action == "ns_sweep":
            n = rng.randrange(2, 8)
            wave += 1
            _both(oracle, dev, lambda s, n=n, w=wave: [
                s.clientset.create_pod(
                    _pod(f"ns{w}-{i}", ns=f"ns-{i % 3}"))
                for i in range(n)])
    _assert_identical(oracle, dev, ctx=f"(seed {seed})")
    assert dev.hint_hits > 0, "fuzz never engaged the hint path"


class TestHintLru:
    """The 2-way signature-keyed LRU (ISSUE 19 satellite): alternating
    deployment shapes keep BOTH on the host path; TPU_SCHED_HINT_LRU=1 is
    the single-slot A/B baseline. Exactness is non-negotiable either way —
    every scenario holds the always-dispatch oracle equivalence."""

    def test_two_shapes_alternate_without_thrash(self):
        """Two replica shapes interleaving through one queue bind with
        ZERO device dispatches after seeding — the single-slot cache would
        thrash (each shape evicting the other every pod). Cross-entry
        coherence rides along: both entries place onto the SAME nodes, so
        any stale sibling row would diverge from the oracle here."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-a-{i}", cpu="200m")) for i in range(6)])
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-b-{i}", cpu="400m")) for i in range(6)])
        assert len(dev._hints.entries) == 2
        b0, h0 = dev.device_batches, dev.hint_hits
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"alt-{i}", cpu=("200m" if i % 2 == 0 else "400m")))
            for i in range(40)])
        _assert_identical(oracle, dev)
        assert dev.device_batches == b0, "alternating shapes thrashed"
        assert dev.hint_hits - h0 >= 40

    def test_lru_capacity_one_is_the_single_slot_baseline(self, monkeypatch):
        """TPU_SCHED_HINT_LRU=1 (the A/B seam): the second shape's install
        evicts the first (counted, labeled lru_evict) and only one entry is
        ever live — the historical behavior, still oracle-exact."""
        monkeypatch.setenv("TPU_SCHED_HINT_LRU", "1")
        oracle, dev = _pair()
        assert dev._hints.capacity == 1
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-a-{i}", cpu="200m")) for i in range(6)])
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-b-{i}", cpu="400m")) for i in range(6)])
        assert len(dev._hints.entries) == 1
        assert dev.metrics.hint_cache_invalidations.value("lru_evict") >= 1
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"alt-{i}", cpu=("200m" if i % 2 == 0 else "400m")))
            for i in range(20)])
        _assert_identical(oracle, dev)

    def test_third_shape_evicts_coldest(self):
        """At capacity 2 a third shape pushes out the least-recently-used
        entry; the two survivors keep serving dispatch-free."""
        oracle, dev = _pair()
        for shape, cpu in (("a", "200m"), ("b", "400m"), ("c", "600m")):
            _both(oracle, dev, lambda s, shape=shape, cpu=cpu: [
                s.clientset.create_pod(_pod(f"seed-{shape}-{i}", cpu=cpu))
                for i in range(6)])
        assert len(dev._hints.entries) == 2
        assert dev.metrics.hint_cache_invalidations.value("lru_evict") >= 1
        b0 = dev.device_batches
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"rep-c-{i}", cpu="600m")) for i in range(10)])
        _assert_identical(oracle, dev)
        assert dev.device_batches == b0

    def test_conflict_blocks_row_on_every_entry(self):
        """Bind-409 semantics under the LRU: the conflicted NODE is blocked
        on every live entry (each one's view understates the winner's
        usage), and every entry survives with just that row fenced."""
        oracle, dev = _pair()
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-a-{i}", cpu="200m")) for i in range(6)])
        _both(oracle, dev, lambda s: [s.clientset.create_pod(
            _pod(f"seed-b-{i}", cpu="400m")) for i in range(6)])
        es = list(dev._hints.entries)
        assert len(es) == 2
        dev._hints.note_conflict("node-3")
        assert len(dev._hints.entries) == 2
        for e in es:
            row = e.row_of["node-3"]
            assert e.blocked[row] and not e.ok[row]
