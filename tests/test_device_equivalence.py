"""Device↔host equivalence: the TPU batch kernel must produce IDENTICAL
pod→node assignments to the host-oracle sequential scheduler on randomized
cluster states (SURVEY.md §4 'device/host equivalence suite'; the
"identical pod→node assignments" requirement in BASELINE.json).

Both paths run with deterministic_ties so reservoir tie-breaking can't
diverge; everything else — adaptive sampling, rotation, integer score math —
must line up exactly.
"""

import random

import pytest

from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


def _mk_cluster(sched, n_nodes, seed=0, zones=4, taint_frac=0.0, unsched_frac=0.0):
    rng = random.Random(seed)
    for i in range(n_nodes):
        b = (make_node().name(f"node-{i}")
             .capacity({"cpu": rng.choice([2, 4, 8, 16]),
                        "memory": f"{rng.choice([4, 8, 16, 32])}Gi",
                        "pods": 110})
             .zone(f"zone-{i % zones}")
             .label("disk", rng.choice(["ssd", "hdd"])))
        if taint_frac and rng.random() < taint_frac:
            b = b.taint("dedicated", "infra", "NoSchedule")
        if unsched_frac and rng.random() < unsched_frac:
            b = b.unschedulable()
        sched.clientset.create_node(b.obj())


def _assignments(sched):
    return {p.name: p.node_name for p in sched.clientset.pods.values()}


def _run_pair(n_nodes, pods_fn, seed=0, **cluster_kw):
    host = Scheduler(deterministic_ties=True)
    dev = TPUScheduler()
    _mk_cluster(host, n_nodes, seed=seed, **cluster_kw)
    _mk_cluster(dev, n_nodes, seed=seed, **cluster_kw)
    for p in pods_fn():
        host.clientset.create_pod(p)
    for p in pods_fn():
        dev.clientset.create_pod(p)
    host.run_until_idle()
    dev.run_until_idle()
    a_host = _assignments(host)
    a_dev = _assignments(dev)
    diffs = {k: (a_host[k], a_dev.get(k)) for k in a_host if a_host[k] != a_dev.get(k)}
    assert not diffs, f"host/device assignment divergence: {diffs}"
    return host, dev


def _basic_pods(n, cpu="500m", mem="256Mi", labels=None, build=None):
    def fn():
        pods = []
        for i in range(n):
            b = make_pod().name(f"pod-{i}").req({"cpu": cpu, "memory": mem})
            if labels:
                b = b.labels(dict(labels))
            if build:
                b = build(b)
            pods.append(b.obj())
        return pods
    return fn


class TestFitEquivalence:
    def test_basic_fit_least_allocated(self):
        host, dev = _run_pair(23, _basic_pods(40))
        assert dev.device_scheduled == 40
        assert dev.host_path_pods == 0

    def test_fill_until_infeasible(self):
        # More pods than capacity: both paths must fail the same pods.
        host, dev = _run_pair(5, _basic_pods(30, cpu="2"))
        assert host.scheduled == dev.scheduled
        assert host.failures > 0

    def test_sampling_truncation_rotation(self):
        # >100 nodes triggers numFeasibleNodesToFind truncation + rotation.
        _run_pair(140, _basic_pods(60))

    def test_zero_request_pods(self):
        _run_pair(9, _basic_pods(12, cpu="0", mem="0"))


class TestTaintEquivalence:
    def test_taints_reject(self):
        _run_pair(16, _basic_pods(20), taint_frac=0.5)

    def test_tolerated_taints(self):
        _run_pair(16, _basic_pods(
            20, build=lambda b: b.toleration("dedicated", "infra", "Equal", "NoSchedule")),
            taint_frac=0.5)

    def test_unschedulable_nodes(self):
        _run_pair(16, _basic_pods(20), unsched_frac=0.3)


class TestSelectorEquivalence:
    def test_node_selector(self):
        _run_pair(20, _basic_pods(15, build=lambda b: b.node_selector({"disk": "ssd"})))

    def test_node_name_pin(self):
        def fn():
            return [make_pod().name(f"pin-{i}").req({"cpu": "100m"})
                    .node(f"node-{i % 3}").obj() for i in range(6)]
        _run_pair(8, fn)


class TestSpreadEquivalence:
    def test_do_not_schedule_spread(self):
        _run_pair(12, _basic_pods(
            24, labels={"app": "web"},
            build=lambda b: b.spread_constraint(1, ZONE, "DoNotSchedule", {"app": "web"})))

    def test_schedule_anyway_spread_scoring(self):
        _run_pair(10, _basic_pods(
            20, labels={"app": "api"},
            build=lambda b: b.spread_constraint(1, ZONE, "ScheduleAnyway", {"app": "api"})))

    def test_hostname_spread(self):
        _run_pair(7, _basic_pods(
            14, labels={"app": "db"},
            build=lambda b: b.spread_constraint(2, HOSTNAME, "DoNotSchedule", {"app": "db"})))


class TestAffinityEquivalence:
    def test_required_anti_affinity(self):
        _run_pair(10, _basic_pods(
            8, labels={"app": "solo"},
            build=lambda b: b.pod_affinity(HOSTNAME, {"app": "solo"}, anti=True)))

    def test_required_affinity_bootstrap(self):
        _run_pair(12, _basic_pods(
            9, labels={"app": "pack"},
            build=lambda b: b.pod_affinity(ZONE, {"app": "pack"})))

    def test_preferred_anti_affinity_scoring(self):
        _run_pair(8, _basic_pods(
            16, labels={"app": "spread-me"},
            build=lambda b: b.pod_affinity(ZONE, {"app": "spread-me"}, anti=True, weight=10)))


class TestMixedWorkload:
    def test_mixed_signatures(self):
        """Multiple interleaved deployments → multiple batches per run."""
        def fn():
            pods = []
            for i in range(10):
                pods.append(make_pod().name(f"a-{i}").req({"cpu": "250m", "memory": "128Mi"})
                            .labels({"app": "a"})
                            .spread_constraint(1, ZONE, "DoNotSchedule", {"app": "a"}).obj())
            for i in range(10):
                pods.append(make_pod().name(f"b-{i}").req({"cpu": "1", "memory": "1Gi"})
                            .labels({"app": "b"}).obj())
            for i in range(5):
                pods.append(make_pod().name(f"c-{i}").labels({"app": "c"}).obj())
            return pods
        host, dev = _run_pair(15, fn)
        assert dev.device_batches >= 3


class TestFuzzEquivalence:
    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_clusters(self, seed):
        rng = random.Random(1000 + seed)
        n_nodes = rng.randint(4, 60)

        def fn():
            rng2 = random.Random(2000 + seed)
            pods = []
            n_deploys = rng2.randint(1, 4)
            for d in range(n_deploys):
                n = rng2.randint(1, 12)
                cpu = rng2.choice(["100m", "250m", "1", "2"])
                mem = rng2.choice(["64Mi", "512Mi", "2Gi"])
                labels = {"app": f"d{d}"}
                r = rng2.random()
                for i in range(n):
                    b = (make_pod().name(f"d{d}-{i}")
                         .req({"cpu": cpu, "memory": mem}).labels(dict(labels)))
                    if r < 0.3:
                        b = b.spread_constraint(
                            rng2.choice([1, 2]), ZONE,
                            rng2.choice(["DoNotSchedule", "ScheduleAnyway"]), labels)
                    elif r < 0.5:
                        b = b.pod_affinity(HOSTNAME, labels, anti=True)
                    elif r < 0.6:
                        b = b.node_selector({"disk": "ssd"})
                    pods.append(b.obj())
            return pods

        _run_pair(n_nodes, fn, seed=seed, taint_frac=0.2, unsched_frac=0.1)


class TestSessionEquivalence:
    """The chained-carry session + lap-vectorized kernel against the host
    oracle at scales where adaptive sampling makes multi-pod laps (L>1) and
    multiple chained batches."""

    def test_multi_lap_scale(self):
        # 600 nodes → to_find=max(600*45//100,100)=270 → L=2 laps; enough
        # pods for several chained batches at max_batch=64.
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler(max_batch=64)
        _mk_cluster(host, 600, seed=7)
        _mk_cluster(dev, 600, seed=7)
        for s in (host, dev):
            for p in _basic_pods(300, cpu="250m", mem="128Mi")():
                s.clientset.create_pod(p)
        host.run_until_idle()
        dev.run_until_idle()
        a_host, a_dev = _assignments(host), _assignments(dev)
        diffs = {k: (a_host[k], a_dev.get(k)) for k in a_host if a_host[k] != a_dev.get(k)}
        assert not diffs, f"divergence ({len(diffs)}): {dict(list(diffs.items())[:5])}"
        assert dev.device_batches >= 4
        assert dev.host_path_pods == 0

    def test_lap_boundary_with_infeasible_rows(self):
        # Tight capacities make nodes fill mid-session: feasibility flips
        # inside laps, exercising window-boundary recomputation.
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler(max_batch=32)
        for s in (host, dev):
            for i in range(150):
                s.clientset.create_node(
                    make_node().name(f"node-{i}")
                    .capacity({"cpu": 1, "memory": "1Gi", "pods": 3})
                    .zone(f"zone-{i % 3}").obj())
            for p in _basic_pods(260, cpu="300m", mem="300Mi")():
                s.clientset.create_pod(p)
        host.run_until_idle()
        dev.run_until_idle()
        a_host, a_dev = _assignments(host), _assignments(dev)
        assert a_host == a_dev
        assert host.scheduled == dev.scheduled

    def test_churn_between_runs_invalidates_session(self):
        # Node add mid-workload: the session must abandon the device carry
        # (cluster_event_seq) and still match a host run seeing the same
        # sequence.
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler(max_batch=16)
        for s in (host, dev):
            for i in range(120):
                s.clientset.create_node(
                    make_node().name(f"node-{i}").capacity({"cpu": 8, "pods": 20})
                    .zone(f"zone-{i % 4}").obj())
            for p in _basic_pods(48)():
                s.clientset.create_pod(p)
            s.run_until_idle()
            # churn: new node + another wave
            s.clientset.create_node(
                make_node().name("late-node").capacity({"cpu": 8, "pods": 20})
                .zone("zone-0").obj())
            for i in range(48):
                s.clientset.create_pod(
                    make_pod().name(f"wave2-{i}").req({"cpu": "500m", "memory": "256Mi"}).obj())
            s.run_until_idle()
        assert _assignments(host) == _assignments(dev)


class TestWidenedCoverageEquivalence:
    """Round-3 kernel coverage: node-affinity expressions, preferred node
    affinity, host ports, image locality, NodeDeclaredFeatures — previously
    host-path fallbacks, now device-evaluated via host-built static vectors
    (ops/features.py sel_match / na_raw / extra_ok / il_score). Reference:
    nodeaffinity/node_affinity.go, nodeports/, imagelocality/."""

    def test_node_affinity_expressions(self):
        host, dev = _run_pair(24, _basic_pods(
            18, build=lambda b: b.node_affinity_in("disk", ["ssd"])))
        assert dev.host_path_pods == 0

    def test_node_affinity_hostname_label(self):
        # Required affinity over the hostname LABEL (matchExpressions):
        # static per batch, rides the device via sel_match.
        def fn():
            pods = []
            for i in range(8):
                b = make_pod().name(f"ds-{i}").req({"cpu": "100m"})
                b = b.node_affinity_in("kubernetes.io/hostname", [f"node-{i % 4}"])
                pods.append(b.obj())
            return pods
        host, dev = _run_pair(12, fn)
        assert dev.host_path_pods == 0

    def test_node_affinity_match_fields_narrowing(self):
        # Daemonset shape: matchFields metadata.name pin (daemonset-pod.yaml)
        # triggers the NodeAffinity PreFilterResult narrowing, which changes
        # the rotation/sampling universe — these pods MUST take the host path
        # (batch_supported), and assignments must still match the oracle.
        from kubernetes_tpu.api.labels import IN, Requirement
        from kubernetes_tpu.api.types import Affinity, NodeAffinity as NA, NodeSelector, NodeSelectorTerm

        def fn():
            pods = []
            for i in range(10):
                p = make_pod().name(f"ds-{i}").req({"cpu": "100m"}).obj()
                term = NodeSelectorTerm(match_fields=(
                    Requirement("metadata.name", IN, (f"node-{i % 4}",)),))
                p.affinity = Affinity(node_affinity=NA(required=NodeSelector((term,))))
                pods.append(p)
            return pods
        host, dev = _run_pair(12, fn)
        assert dev.host_path_pods == 10  # PreFilterResult narrowing: host path

    def test_preferred_node_affinity_scoring(self):
        host, dev = _run_pair(20, _basic_pods(
            16, build=lambda b: b.preferred_node_affinity(7, "disk", ["hdd"])))
        assert dev.host_path_pods == 0

    def test_host_ports_self_blocking(self):
        # Identical pods with a host port: at most one per node; both paths
        # must fail the overflow pods identically.
        host, dev = _run_pair(6, _basic_pods(
            9, cpu="100m", build=lambda b: b.host_port(8080)))
        # The 6 placements ride the device; the 3 infeasible overflow pods
        # intentionally re-run host-side for the exact FitError diagnosis.
        assert dev.device_scheduled == 6
        assert host.scheduled == dev.scheduled == 6
        assert host.failures > 0

    def test_image_locality_scoring(self):
        def cluster(sched):
            for i in range(15):
                b = (make_node().name(f"node-{i}")
                     .capacity({"cpu": 8, "memory": "32Gi", "pods": 110}))
                if i % 3 == 0:
                    b = b.image("registry/app:v1", 400 * 1024 * 1024)
                sched.clientset.create_node(b.obj())
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler()
        cluster(host)
        cluster(dev)
        def pods():
            return [make_pod().name(f"p-{i}").req({"cpu": "100m"})
                    .image("registry/app:v1").obj() for i in range(10)]
        for p in pods():
            host.clientset.create_pod(p)
        for p in pods():
            dev.clientset.create_pod(p)
        host.run_until_idle()
        dev.run_until_idle()
        assert _assignments(host) == _assignments(dev)
        assert dev.host_path_pods == 0

    def test_node_declared_features(self):
        # NDF is feature-gated off by default (reference kube_features.go):
        # build a profile that enables the plugin on both paths.
        from kubernetes_tpu.core.registry import DEFAULT_PLUGINS, build_framework
        plugins = DEFAULT_PLUGINS + (("NodeDeclaredFeatures", 0),)
        factory = lambda h: {"default-scheduler": build_framework(h, plugins=plugins)}  # noqa: E731

        def cluster(sched):
            for i in range(12):
                b = (make_node().name(f"node-{i}")
                     .capacity({"cpu": 8, "memory": "32Gi", "pods": 110}))
                n = b.obj()
                if i % 2 == 0:
                    n.declared_features = {"feat.a": True, "feat.b": True}
                sched.clientset.create_node(n)
        host = Scheduler(deterministic_ties=True, profile_factory=factory)
        dev = TPUScheduler(profile_factory=factory)
        cluster(host)
        cluster(dev)
        def pods():
            out = []
            for i in range(8):
                p = make_pod().name(f"p-{i}").req({"cpu": "100m"}).obj()
                p.annotations["features.k8s.io/required"] = "feat.a,feat.b"
                out.append(p)
            return out
        for p in pods():
            host.clientset.create_pod(p)
        for p in pods():
            dev.clientset.create_pod(p)
        host.run_until_idle()
        dev.run_until_idle()
        assert _assignments(host) == _assignments(dev)
        assert dev.host_path_pods == 0
        bound = {n for n in _assignments(dev).values() if n}
        assert all(int(n.split("-")[1]) % 2 == 0 for n in bound)


class TestInfeasibleDiagnosisEquivalence:
    """Device-infeasible pods produce the same outcome (failure accounting,
    unschedulable plugin attribution for queueing hints, preemption
    PostFilter behavior) whether diagnosed by the vectorized mirror path or
    the host rerun — and identical floods don't tear down the session."""

    def test_flood_outcomes_match_host(self):
        def pods():
            out = []
            for i in range(25):
                out.append(make_pod().name(f"flood-{i}").req({"cpu": "900"}).obj())
            for i in range(30):
                out.append(make_pod().name(f"ok-{i}").req({"cpu": "100m"}).obj())
            return out
        host, dev = _run_pair(30, pods)
        assert host.scheduled == dev.scheduled == 30
        assert host.failures == dev.failures == 25
        h_plugins = {q.uid: tuple(sorted(q.unschedulable_plugins))
                     for q in host.queue.unschedulable.values()}
        d_plugins = {q.uid: tuple(sorted(q.unschedulable_plugins))
                     for q in dev.queue.unschedulable.values()}
        assert set(h_plugins.values()) == set(d_plugins.values())

    def test_preemptable_infeasible_still_preempts(self):
        # Infeasible only because nodes are FULL (not over-capacity): the
        # diagnosis must leave preemption viable and the high-priority pod
        # must evict a victim on both paths.
        def build(cls):
            from kubernetes_tpu.core import FakeClientset
            cs = FakeClientset()
            s = cls(clientset=cs) if cls is TPUScheduler else cls(
                clientset=cs, deterministic_ties=True)
            for i in range(3):
                cs.create_node(make_node().name(f"n{i}").capacity(
                    {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
            for i in range(3):
                p = make_pod().name(f"low-{i}").req({"cpu": "4"}).priority(1).obj()
                p.node_name = f"n{i}"
                cs.create_pod(p)
            hi = make_pod().name("hi").req({"cpu": "4"}).priority(50).obj()
            cs.create_pod(hi)
            s.run_until_idle()
            return cs, s, hi
        cs_h, s_h, hi_h = build(Scheduler)
        cs_d, s_d, hi_d = build(TPUScheduler)
        assert hi_h.node_name and hi_d.node_name
        assert hi_h.node_name == hi_d.node_name

    def test_fail_memo_does_not_park_higher_priority_pod(self):
        """A memoized terminal failure must not serve a later pod whose
        priority differs: PostFilter preemption eligibility depends on
        priority (victims in [memo_prio, new_prio) become evictable), so the
        higher-priority pod must run its own attempt — and preempt."""
        from kubernetes_tpu.core import FakeClientset
        cs = FakeClientset()
        s = TPUScheduler(clientset=cs)
        for i in range(2):
            cs.create_node(make_node().name(f"n{i}").capacity(
                {"cpu": 4, "memory": "16Gi", "pods": 110}).obj())
        for i in range(2):
            p = make_pod().name(f"mid-{i}").req({"cpu": "4"}).priority(10).obj()
            p.node_name = f"n{i}"
            cs.create_pod(p)
        # Flood of same-priority hopeless pods primes the memo...
        for i in range(5):
            cs.create_pod(make_pod().name(f"same-{i}").req({"cpu": "4"})
                          .priority(10).obj())
        s.run_until_idle()
        assert s.scheduled == 0
        # ...then an identically-signed HIGHER-priority pod must not be
        # parked from the memo: preemption can make room for it.
        hi = make_pod().name("hi").req({"cpu": "4"}).priority(50).obj()
        cs.create_pod(hi)
        s.run_until_idle()
        assert hi.nominated_node_name or hi.node_name, (
            "higher-priority pod was parked by a stale fail memo")


class TestNominatedLane:
    """Nominated pods ride the kernel as a fit-filter lane
    (runtime/framework.go:1275 two-pass, pass 1 resources) instead of
    disabling the device path wholesale (round-4 VERDICT item 3)."""

    def _pair(self, n_nodes=8, seed=0):
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler()
        _mk_cluster(host, n_nodes, seed=seed)
        _mk_cluster(dev, n_nodes, seed=seed)
        return host, dev

    def test_manual_nominations_match_host(self):
        from kubernetes_tpu.core.node_info import PodInfo
        host, dev = self._pair()
        for sched in (host, dev):
            g1 = make_pod().name("ghost1").req({"cpu": "1500m"}).priority(50).obj()
            g2 = make_pod().name("ghost2").req({"cpu": "1"}).priority(50).obj()
            sched.queue.nominator.add_nominated_pod(PodInfo.of(g1), "node-0")
            sched.queue.nominator.add_nominated_pod(PodInfo.of(g2), "node-3")
        proto = make_pod().name("proto").req({"cpu": "500m"}).labels({"a": "b"}).obj()
        for sched in (host, dev):
            for i in range(24):
                sched.clientset.create_pod(proto.clone_from_template(f"p{i}"))
            sched.run_until_idle()
        a_h, a_d = _assignments(host), _assignments(dev)
        assert a_h == a_d
        assert dev.device_scheduled >= 20, (
            f"device path should stay on with nominations "
            f"(device={dev.device_scheduled}, host={dev.host_path_pods})")

    def test_lower_priority_nomination_ignored(self):
        """Only >=-priority nominations count in pass 1
        (framework.go:1280-1284): a LOWER-priority nomination must not
        shrink the fit room for the batch."""
        from kubernetes_tpu.core.node_info import PodInfo
        host, dev = self._pair()
        for sched in (host, dev):
            g = make_pod().name("ghost").req({"cpu": "100"}).priority(-5).obj()
            sched.queue.nominator.add_nominated_pod(PodInfo.of(g), "node-1")
        proto = make_pod().name("proto").req({"cpu": "500m"}).obj()
        for sched in (host, dev):
            for i in range(16):
                sched.clientset.create_pod(proto.clone_from_template(f"p{i}"))
            sched.run_until_idle()
        assert _assignments(host) == _assignments(dev)
        assert dev.device_scheduled >= 14

    def test_preemption_nominations_interleaved(self):
        """The VERDICT done-criterion: real PostFilter preemptions create
        nominations mid-workload; plain pods keep riding the device with
        identical assignments and >=90% device-scheduled."""
        host = Scheduler(deterministic_ties=True)
        dev = TPUScheduler()
        for sched in (host, dev):
            for i in range(10):
                sched.clientset.create_node(
                    make_node().name(f"node-{i}")
                    .capacity({"cpu": 4, "memory": "8Gi", "pods": 20}).obj())
        # fill the cluster with evictable low-priority pods
        low = make_pod().name("low").req({"cpu": "3"}).priority(0).obj()
        for sched in (host, dev):
            for i in range(10):
                sched.clientset.create_pod(low.clone_from_template(f"low-{i}"))
            sched.run_until_idle()
        # preemptors (high priority, need 3 cpu -> must evict) interleaved
        # with plain small pods that fit in the remaining 1-cpu slivers
        hi = make_pod().name("hi").req({"cpu": "3"}).priority(100).obj()
        small = make_pod().name("small").req({"cpu": "200m"}).priority(10).obj()
        for sched in (host, dev):
            for i in range(3):
                sched.clientset.create_pod(hi.clone_from_template(f"hi-{i}"))
                for j in range(8):
                    sched.clientset.create_pod(
                        small.clone_from_template(f"small-{i}-{j}"))
                sched.run_until_idle()
            # let evictions finish and preemptors land
            for _ in range(40):
                sched.process_async_api_errors()
                sched.run_until_idle()
        a_h, a_d = _assignments(host), _assignments(dev)
        small_h = {k: v for k, v in a_h.items() if k.startswith("small")}
        small_d = {k: v for k, v in a_d.items() if k.startswith("small")}
        assert small_h == small_d
        total_small = 24
        assert sum(1 for v in small_d.values() if v) == total_small
        assert dev.device_scheduled >= 0.9 * total_small, (
            f"{dev.device_scheduled} device vs {dev.host_path_pods} host")
