"""Durable control plane (core/wal.py + core/apiserver.py data_dir): WAL
append/replay, snapshot compaction, torn-record handling, epoch + rv
persistence, watch resume across a server restart, bind replay idempotency,
and the scheduler's assumed-vs-recovered-truth reconciliation."""

import json
import os
import time

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import (APIServer, HTTPClientset,
                                           node_from_wire, node_to_wire,
                                           pod_from_wire, pod_to_wire)
from kubernetes_tpu.core.backoff import RetryConfig
from kubernetes_tpu.core.clientset import RetryingClientset
from kubernetes_tpu.core.wal import DurableStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n, cpu=8):
    return [make_node().name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110})
            .zone(f"z{i % 2}").obj() for i in range(n)]


def _pods(n):
    proto = (make_pod().name("proto").req({"cpu": "500m", "memory": "128Mi"})
             .labels({"app": "wal"}).obj())
    return [proto.clone_from_template(f"p{i}") for i in range(n)]


def _serve_on(api, port, timeout=20.0):
    """Bind a (re)started server to a specific port, riding out TIME_WAIT."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return api.serve(port)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


# ---------------------------------------------------------------------------
# WAL + snapshot mechanics (core/wal.py units)
# ---------------------------------------------------------------------------


class TestDurableStore:
    def test_append_replay_roundtrip(self, tmp_path):
        d = str(tmp_path / "s")
        ds = DurableStore(d)
        ds.init_epoch("abc123")
        recs = [{"kind": "pods", "type": "ADDED", "rv": i, "object": {"i": i}}
                for i in range(1, 6)]
        assert ds.load() == (None, [])
        for r in recs:
            ds.append(r)
        ds.close()
        ds2 = DurableStore(d)
        assert ds2.epoch == "abc123"
        snap, replayed = ds2.load()
        assert snap is None and replayed == recs
        assert ds2.torn_records_discarded == 0
        ds2.close()

    def test_snapshot_compaction_resets_wal(self, tmp_path):
        d = str(tmp_path / "s")
        ds = DurableStore(d, snapshot_every=3)
        ds.load()
        for i in range(1, 4):
            ds.append({"kind": "nodes", "type": "ADDED", "rv": i,
                       "object": {}})
        assert ds.should_compact()
        ds.write_snapshot({"seq": {"nodes": 3}, "marker": "compacted"})
        assert not ds.should_compact() and ds.compactions == 1
        ds.append({"kind": "nodes", "type": "ADDED", "rv": 4, "object": {}})
        ds.close()
        ds2 = DurableStore(d)
        snap, recs = ds2.load()
        assert snap["marker"] == "compacted"
        assert [r["rv"] for r in recs] == [4]  # WAL holds only the tail
        ds2.close()

    def test_torn_final_record_discarded_and_truncated(self, tmp_path):
        d = str(tmp_path / "s")
        ds = DurableStore(d)
        ds.load()
        ds.append({"kind": "pods", "type": "ADDED", "rv": 1, "object": {}})
        ds.append({"kind": "pods", "type": "ADDED", "rv": 2, "object": {}})
        ds.close()
        wal = os.path.join(d, DurableStore.WAL)
        with open(wal, "ab") as fh:
            fh.write(b'{"kind": "pods", "type": "ADD')  # kill -9 mid-write
        ds2 = DurableStore(d)
        _, recs = ds2.load()
        assert [r["rv"] for r in recs] == [1, 2]
        assert ds2.torn_records_discarded == 1
        # the torn frame was truncated away: appends resume a clean log
        ds2.append({"kind": "pods", "type": "ADDED", "rv": 3, "object": {}})
        ds2.close()
        ds3 = DurableStore(d)
        _, recs = ds3.load()
        assert [r["rv"] for r in recs] == [1, 2, 3]
        assert ds3.torn_records_discarded == 0
        ds3.close()

    def test_crc_bit_flip_fuzz_quarantines_middle_records(self, tmp_path):
        """Per-record CRC32 (ISSUE 17): flip ONE bit anywhere in a MIDDLE
        record's body/trailer and recovery must raise WALQuarantineError
        naming the file and the damaged record's offset, leave the WAL
        byte-for-byte intact (no truncation — the damage is inspectable,
        and every acked record AFTER it is still on disk), and count the
        failure. Truncation is reserved for the torn TAIL; silent
        mid-log truncation would throw away acked writes."""
        import random

        from kubernetes_tpu.core import wire
        from kubernetes_tpu.core.wal import WALQuarantineError

        d = str(tmp_path / "s")
        ds = DurableStore(d)
        ds.load()
        for i in range(1, 9):
            ds.append({"kind": "pods", "type": "ADDED", "rv": i,
                       "object": {"name": f"p{i}", "uid": f"p{i}",
                                  "payload": "x" * 64}})
        ds.close()
        wal = os.path.join(d, DurableStore.WAL)
        with open(wal, "rb") as fh:
            pristine = fh.read()
        # Frame boundaries off the pristine log (wire.scan is the same
        # sniffer recovery uses).
        bounds, pos = [], 0
        while pos < len(pristine):
            _, nxt = wire.scan(pristine, pos)
            bounds.append((pos, nxt))
            pos = nxt
        assert len(bounds) == 8
        rng = random.Random(0xC4C)
        for trial in range(20):
            start, end = bounds[rng.randrange(1, len(bounds) - 1)]
            # Skip MAGIC/VERSION + up to 5 varint bytes: header damage is
            # indistinguishable from a torn tail (documented limitation);
            # body + CRC trailer damage must quarantine.
            off = rng.randrange(start + 7, end)
            bit = 1 << rng.randrange(8)
            damaged = bytearray(pristine)
            damaged[off] ^= bit
            with open(wal, "wb") as fh:
                fh.write(damaged)
            ds2 = DurableStore(d)
            with pytest.raises(WALQuarantineError) as ei:
                ds2.load()
            assert ds2.crc_failures == 1
            assert ei.value.path == wal
            assert ei.value.offset == start, (trial, off, start)
            with open(wal, "rb") as fh:
                assert fh.read() == bytes(damaged), \
                    "quarantine must not truncate or rewrite the WAL"
        # Repairing the damage (restoring the pristine bytes) recovers
        # every record — nothing after the quarantine point was lost.
        with open(wal, "wb") as fh:
            fh.write(pristine)
        ds3 = DurableStore(d)
        _, recs = ds3.load()
        assert [r["rv"] for r in recs] == list(range(1, 9))
        assert ds3.crc_failures == 0
        ds3.close()

    def test_crc_failure_metric_surfaces_on_apiserver(self, tmp_path):
        """apiserver_wal_crc_failures_total rides expose_metrics off the
        persistence counter (0 on a healthy boot)."""
        d = str(tmp_path / "s")
        api = APIServer(data_dir=d)
        assert "apiserver_wal_crc_failures_total 0" in api.expose_metrics()


# ---------------------------------------------------------------------------
# apiserver recovery (snapshot+WAL replay, rv/epoch resume)
# ---------------------------------------------------------------------------


def test_apiserver_recovers_store_rv_and_epoch(tmp_path):
    d = str(tmp_path / "state")
    api = APIServer(data_dir=d, snapshot_every=7)  # exercises compaction too
    for n in _nodes(3):
        api.store.create_node(n)
    pods = _pods(6)
    for p in pods:
        api.store.create_pod(p)
    api.store.bind(pods[0], "n0")
    api.store.bind(pods[1], "n1")
    api.store.delete_pod(pods[5])
    epoch, seq = api.epoch, dict(api._seq)
    api.shutdown()

    api2 = APIServer(data_dir=d)
    assert api2.epoch == epoch              # persisted boot epoch re-announced
    assert dict(api2._seq) == seq           # rv counters resume, not restart
    assert set(api2.store.nodes) == {"n0", "n1", "n2"}
    assert len(api2.store.pods) == 5        # the deleted pod stayed deleted
    assert api2.store.bindings == {pods[0].uid: "n0", pods[1].uid: "n1"}
    assert api2.persistence.compactions == 0  # fresh instance, fresh counter
    # recovered backlog serves incremental resumes: a new write mints the
    # NEXT rv, never a duplicate
    before = api2._seq["pods"]
    api2.store.create_pod(_pods(1)[0].clone_from_template("fresh"))
    assert api2._seq["pods"] == before + 1
    api2.shutdown()


def test_watch_resume_across_restart_same_epoch(tmp_path):
    """A reflector that survives the server's death reconnects with its last
    rv + the PERSISTED epoch and is served RESUME — no Replace re-list."""
    d = str(tmp_path / "state")
    api = APIServer(data_dir=d)
    port = api.serve(0)
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    try:
        for n in _nodes(2):
            client.create_node(n)
        for p in _pods(4):
            client.create_pod(p)
        deadline = time.monotonic() + 10
        while len(client.pods) < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(client.pods) == 4
        relists = dict(client.relists)
        api.shutdown()  # process death analogue: streams EOF, state on disk

        api2 = APIServer(data_dir=d)
        _serve_on(api2, port)
        try:
            pod = _pods(1)[0].clone_from_template("after-restart")
            client.create_pod(pod)
            deadline = time.monotonic() + 20
            while (pod.uid not in client.pods
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert pod.uid in client.pods  # live events flow again
            assert client.resumes["pods"] >= 1
            assert client.resumes["nodes"] >= 1
            assert dict(client.relists) == relists  # RESUME, never Replace
            assert api2.resumed_watches >= 2
        finally:
            api2.shutdown()
    finally:
        client.close()


def test_bind_replay_idempotent_conflict_409(tmp_path):
    """A retried bind whose first reply was lost lands as an idempotent
    same-node 200; a bind to a DIFFERENT node is a 409 conflict (a pod must
    never be bound twice)."""
    from urllib import request as urlrequest
    from urllib.error import HTTPError

    api = APIServer()
    port = api.serve(0)
    base = f"http://127.0.0.1:{port}"

    def call(method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urlrequest.Request(base + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        call("POST", "/api/v1/nodes", node_to_wire(_nodes(1)[0]))
        pod = _pods(1)[0]
        call("POST", "/api/v1/pods", pod_to_wire(pod))
        assert call("POST", f"/api/v1/pods/{pod.uid}/binding",
                    {"node": "n0"}) == {"bound": True}
        seq_after_bind = api._seq["pods"]
        # replay (lost reply): idempotent, no re-fired MODIFIED event
        assert call("POST", f"/api/v1/pods/{pod.uid}/binding",
                    {"node": "n0"}) == {"bound": True}
        assert api._seq["pods"] == seq_after_bind
        with pytest.raises(HTTPError) as ei:
            call("POST", f"/api/v1/pods/{pod.uid}/binding", {"node": "other"})
        assert ei.value.code == 409
        assert api.bind_conflicts == 1
        assert api.store.bindings[pod.uid] == "n0"
    finally:
        api.shutdown()


def test_nomination_status_patch_survives_restart(tmp_path):
    """Status patches fan out no watch event, but the scheduling-relevant
    slice (nominatedNodeName) is WAL'd as an rv-less STATUS record: a
    restart recovers it, and the record never enters the watch backlog."""
    from urllib import request as urlrequest

    def call(base, method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urlrequest.Request(base + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    d = str(tmp_path / "state")
    api = APIServer(data_dir=d)
    port = api.serve(0)
    base = f"http://127.0.0.1:{port}"
    pod = _pods(1)[0]
    try:
        call(base, "POST", "/api/v1/nodes", node_to_wire(_nodes(1)[0]))
        call(base, "POST", "/api/v1/pods", pod_to_wire(pod))
        call(base, "POST", f"/api/v1/pods/{pod.uid}/status",
             {"nominatedNodeName": "n0"})
        assert api.store.pods[pod.uid].nominated_node_name == "n0"
    finally:
        api.shutdown()

    api2 = APIServer(data_dir=d)
    assert api2.store.pods[pod.uid].nominated_node_name == "n0"
    # rv-less STATUS records replay into the store (and the watch-cache
    # object snapshot) but never the resume ring
    assert all(rv is not None
               for rv, _e, _d in api2.watch_cache["pods"]._ring)
    assert api2.watch_cache["pods"].get(
        pod.uid)["nominatedNodeName"] == "n0"
    api2.shutdown()


# ---------------------------------------------------------------------------
# scheduler post-restart reconciliation (assumed-vs-recovered truth)
# ---------------------------------------------------------------------------


def test_scheduler_unwinds_lost_binds_against_recovered_truth():
    """An apiserver that comes back WITHOUT the scheduler's bindings (the
    lost-bind recovery shape: restart from a stale store): the reflector's
    re-list reports the pods unbound, the scheduler diffs that against its
    cache (assumed + bound placements), unwinds the phantoms, and rebinds
    everything against the recovered truth."""
    api = APIServer()
    port = api.serve(0)
    node_wires = [node_to_wire(n) for n in _nodes(4)]
    pod_wires = [pod_to_wire(p) for p in _pods(6)]
    for w in node_wires:
        api.store.create_node(node_from_wire(w))
    for w in pod_wires:
        api.store.create_pod(pod_from_wire(w))
    client = HTTPClientset(f"http://127.0.0.1:{port}")
    sched = Scheduler(
        clientset=RetryingClientset(client, retry=RetryConfig(
            initial_backoff=0.02, max_backoff=0.2, max_attempts=8, seed=3)),
        deterministic_ties=True)
    api2 = None
    try:
        deadline = time.monotonic() + 30
        while len(api.store.bindings) < 6 and time.monotonic() < deadline:
            sched.run_until_idle()
            time.sleep(0.01)
        assert len(api.store.bindings) == 6
        first_truth = dict(api.store.bindings)
        api.shutdown()

        # Amnesiac restart: same objects, NO bindings.
        api2 = APIServer()
        for w in node_wires:
            api2.store.create_node(node_from_wire(w))
        for w in pod_wires:
            api2.store.create_pod(pod_from_wire(w))
        _serve_on(api2, port)

        deadline = time.monotonic() + 60
        while len(api2.store.bindings) < 6 and time.monotonic() < deadline:
            sched.run_until_idle()
            time.sleep(0.01)
        assert sched.reconcile_unwinds >= 6      # every phantom was unwound
        assert len(api2.store.bindings) == 6     # ...and re-committed
        # every pod rebound exactly once, onto real nodes (exact placements
        # may legitimately rotate: the reschedule continues the rotation
        # index where the first run left it)
        assert set(api2.store.bindings) == set(first_truth)
        assert all(n in api2.store.nodes for n in api2.store.bindings.values())
        # the balanced workload still spreads one pod short of everywhere
        assert len(set(api2.store.bindings.values())) == 4
        # the cache converges on the recovered truth (no stale phantoms) —
        # drain the in-flight bind-confirm events first
        deadline = time.monotonic() + 15
        while sched.cache.assumed_pods and time.monotonic() < deadline:
            sched.run_until_idle()
            time.sleep(0.01)
        assert len(sched.cache.assumed_pods) == 0
    finally:
        client.close()
        for a in (api, api2):
            if a is not None:
                a.shutdown()
