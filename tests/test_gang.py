"""Gang (PodGroup) scheduling: all-or-nothing group cycles with snapshot
simulation and LIFO revert (reference schedule_one_podgroup.go)."""

from kubernetes_tpu.api.types import PodGroup
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(s, n, cpu="4"):
    for i in range(n):
        s.clientset.create_node(
            make_node().name(f"node-{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": 10}).obj())


def _group_pods(s, name, count, cpu="1"):
    for i in range(count):
        p = make_pod().name(f"{name}-{i}").req({"cpu": cpu}).obj()
        p.pod_group = name
        s.clientset.create_pod(p)


class TestGangScheduling:
    def test_group_waits_for_min_count(self):
        s = Scheduler()
        _nodes(s, 2)
        s.clientset.create_pod_group(PodGroup(name="gang", min_count=3))
        _group_pods(s, "gang", 2)
        s.run_until_idle()
        assert s.scheduled == 0  # only 2 of 3 members present
        _group_pods_extra = make_pod().name("gang-late").req({"cpu": "1"}).obj()
        _group_pods_extra.pod_group = "gang"
        s.clientset.create_pod(_group_pods_extra)
        s.run_until_idle()
        assert s.scheduled == 3

    def test_all_or_nothing_revert(self):
        """Group needing more capacity than exists schedules NO members."""
        s = Scheduler()
        _nodes(s, 1, cpu="2")
        s.clientset.create_pod_group(PodGroup(name="big", min_count=3))
        _group_pods(s, "big", 3, cpu="1")  # needs 3 cpu, node has 2
        s.run_until_idle()
        assert s.scheduled == 0
        assert not s.clientset.bindings
        # Snapshot must be clean: a fitting individual pod still schedules.
        s.clientset.create_pod(make_pod().name("solo").req({"cpu": "2"}).obj())
        s.run_until_idle()
        assert len(s.clientset.bindings) == 1

    def test_group_schedules_atomically(self):
        s = Scheduler()
        _nodes(s, 3, cpu="2")
        s.clientset.create_pod_group(PodGroup(name="trio", min_count=3))
        _group_pods(s, "trio", 3, cpu="2")
        s.run_until_idle()
        assert s.scheduled == 3
        nodes_used = set(s.clientset.bindings.values())
        assert len(nodes_used) == 3  # one full node each

    def test_group_retry_after_node_add(self):
        s = Scheduler()
        _nodes(s, 1, cpu="2")
        s.clientset.create_pod_group(PodGroup(name="pair", min_count=2))
        _group_pods(s, "pair", 2, cpu="2")
        s.run_until_idle()
        assert s.scheduled == 0
        _nodes_extra = make_node().name("node-extra").capacity(
            {"cpu": "2", "memory": "8Gi", "pods": 10}).obj()
        s.clientset.create_node(_nodes_extra)
        s.run_until_idle()
        assert s.scheduled == 2

    def test_gang_through_tpu_scheduler(self):
        """Gang entities fall back to the host group cycle in the device
        pipeline; plain pods still batch on device."""
        s = TPUScheduler()
        _nodes(s, 3, cpu="4")
        s.clientset.create_pod_group(PodGroup(name="g", min_count=2))
        _group_pods(s, "g", 2, cpu="1")
        for i in range(4):
            s.clientset.create_pod(
                make_pod().name(f"plain-{i}").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 6
        assert s.device_scheduled >= 4
