"""API object model: quantities, resources, selectors, tolerations."""

from kubernetes_tpu.api import labels, resource
from kubernetes_tpu.api.resource import Resource, cpu_to_milli, parse_quantity, to_int
from kubernetes_tpu.api.types import (
    NO_EXECUTE,
    NO_SCHEDULE,
    PREFER_NO_SCHEDULE,
    Taint,
    Toleration,
    find_matching_untolerated_taint,
)
from kubernetes_tpu.testing import make_node, make_pod


class TestQuantity:
    def test_plain(self):
        assert to_int("2") == 2
        assert to_int(7) == 7

    def test_milli_cpu(self):
        assert cpu_to_milli("100m") == 100
        assert cpu_to_milli("1") == 1000
        assert cpu_to_milli("1.5") == 1500
        assert cpu_to_milli("0.1") == 100

    def test_binary_suffixes(self):
        assert to_int("1Ki") == 1024
        assert to_int("1Mi") == 1024 * 1024
        assert to_int("1.5Gi") == int(1.5 * 1024**3)

    def test_decimal_suffixes(self):
        assert to_int("1k") == 1000
        assert to_int("2M") == 2_000_000

    def test_rounds_up(self):
        assert cpu_to_milli("0.0001") == 1  # sub-milli rounds up


class TestResource:
    def test_from_map(self):
        r = Resource.from_map({"cpu": "500m", "memory": "1Gi", "nvidia.com/gpu": 2})
        assert r.milli_cpu == 500
        assert r.memory == 1024**3
        assert r.scalar_resources["nvidia.com/gpu"] == 2

    def test_add_sub(self):
        a = Resource.from_map({"cpu": "1", "memory": "1Gi"})
        b = Resource.from_map({"cpu": "250m", "memory": "256Mi"})
        a.add(b)
        assert a.milli_cpu == 1250
        a.sub(b)
        assert a.milli_cpu == 1000
        assert a.memory == 1024**3


class TestPodRequest:
    def test_sum_of_containers_plus_overhead(self):
        pod = (make_pod().req({"cpu": "100m"})
               .container_req({"cpu": "200m", "memory": "1Gi"})
               .overhead({"cpu": "50m"}).obj())
        r = pod.resource_request()
        assert r.milli_cpu == 350
        assert r.memory == 1024**3

    def test_init_container_max(self):
        pod = (make_pod().req({"cpu": "100m"})
               .init_req({"cpu": "1"}).obj())
        assert pod.resource_request().milli_cpu == 1000

    def test_sidecar_adds(self):
        pod = (make_pod().req({"cpu": "100m"})
               .init_req({"cpu": "300m"}, sidecar=True).obj())
        assert pod.resource_request().milli_cpu == 400


class TestSelectors:
    def test_match_labels(self):
        sel = labels.LabelSelector.of(match_labels={"app": "web"})
        assert sel.matches({"app": "web", "x": "y"})
        assert not sel.matches({"app": "db"})

    def test_expressions(self):
        sel = labels.LabelSelector.of(match_expressions=[
            labels.Requirement("tier", labels.IN, ("fe", "be")),
            labels.Requirement("canary", labels.DOES_NOT_EXIST),
        ])
        assert sel.matches({"tier": "fe"})
        assert not sel.matches({"tier": "fe", "canary": "yes"})
        assert not sel.matches({"tier": "mid"})

    def test_gt_lt(self):
        sel = labels.LabelSelector.of(match_expressions=[
            labels.Requirement("gen", labels.GT, ("5",)),
        ])
        assert sel.matches({"gen": "7"})
        assert not sel.matches({"gen": "3"})
        assert not sel.matches({"gen": "abc"})

    def test_empty_matches_everything(self):
        assert labels.LabelSelector().matches({"anything": "goes"})


class TestTolerations:
    def test_exists_all(self):
        t = Toleration(operator="Exists")
        assert t.tolerates(Taint(key="k", value="v", effect=NO_SCHEDULE))

    def test_equal(self):
        t = Toleration(key="k", operator="Equal", value="v")
        assert t.tolerates(Taint(key="k", value="v", effect=NO_EXECUTE))
        assert not t.tolerates(Taint(key="k", value="other", effect=NO_SCHEDULE))

    def test_effect_scoped(self):
        t = Toleration(key="k", operator="Exists", effect=NO_SCHEDULE)
        assert t.tolerates(Taint(key="k", effect=NO_SCHEDULE))
        assert not t.tolerates(Taint(key="k", effect=NO_EXECUTE))

    def test_find_untolerated_ignores_prefer(self):
        taints = [Taint(key="soft", effect=PREFER_NO_SCHEDULE)]
        assert find_matching_untolerated_taint(taints, []) is None

    def test_find_untolerated(self):
        taints = [Taint(key="hard", effect=NO_SCHEDULE)]
        found = find_matching_untolerated_taint(taints, [])
        assert found is not None and found.key == "hard"
